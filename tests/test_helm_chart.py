"""Helm chart render tests (charts/karpenter-tpu).

Reference: charts/karpenter/values.yaml:28-37 + templates/ — operators
configure image/resources/ports/replicas through values instead of editing
manifests. The chart restricts itself to plain ``{{ .Values.* }}``
substitutions so `helm template` (CI) and the in-repo renderer
(utils/helmlite.py) agree byte-for-byte; the golden file pins the default
render.
"""

import os

import pytest
import yaml

from karpenter_tpu.utils.helmlite import render_chart

CHART = os.path.join(os.path.dirname(__file__), "..", "charts", "karpenter-tpu")
GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "chart_default.yaml")


def docs_by_kind_name(rendered: str):
    out = {}
    for doc in yaml.safe_load_all(rendered):
        if doc:
            out[(doc["kind"], doc["metadata"]["name"])] = doc
    return out


class TestChartRender:
    def test_default_render_matches_golden(self):
        with open(GOLDEN) as f:
            assert render_chart(CHART) == f.read()

    def test_default_render_is_valid_yaml_with_expected_kinds(self):
        docs = docs_by_kind_name(render_chart(CHART))
        kinds = {k for k, _ in docs}
        assert {"Namespace", "ServiceAccount", "ConfigMap", "Deployment",
                "Service", "ClusterRole", "ClusterRoleBinding",
                "MutatingWebhookConfiguration",
                "ValidatingWebhookConfiguration"} <= kinds
        assert ("Deployment", "karpenter-controller") in docs
        assert ("Deployment", "karpenter-webhook") in docs

    def test_values_plumb_through(self):
        rendered = render_chart(CHART, overrides={
            "namespace": "autoscaling",
            "controller.image": "registry.example/karpenter:9.9.9",
            "controller.replicas": 3,
            "controller.ports.metrics": 9090,
            "controller.tpuChips": 4,
            "clusterName": "prod-1",
            "leaderElect": False,
            "webhook.port": 9443,
        })
        docs = docs_by_kind_name(rendered)
        ctl = docs[("Deployment", "karpenter-controller")]
        spec = ctl["spec"]["template"]["spec"]["containers"][0]
        assert ctl["metadata"]["namespace"] == "autoscaling"
        assert ctl["spec"]["replicas"] == 3
        assert spec["image"] == "registry.example/karpenter:9.9.9"
        assert "--leader-elect=false" in spec["args"]
        assert {"name": "CLUSTER_NAME", "value": "prod-1"} in spec["env"]
        assert spec["ports"][0]["containerPort"] == 9090
        assert spec["resources"]["limits"]["google.com/tpu"] == 4
        svc = docs[("Service", "karpenter-webhook")]
        assert svc["spec"]["ports"][0]["targetPort"] == 9443
        hook = docs[("MutatingWebhookConfiguration",
                     "defaulting.webhook.karpenter.sh")]
        assert hook["webhooks"][0]["clientConfig"]["service"][
            "namespace"] == "autoscaling"

    def test_crds_shipped(self):
        crds = os.listdir(os.path.join(CHART, "crds"))
        assert "karpenter.sh_provisioners.yaml" in crds
        with open(os.path.join(CHART, "crds", crds[0])) as f:
            crd = yaml.safe_load(f)
        assert crd["kind"] == "CustomResourceDefinition"

    def test_unknown_values_key_fails_loudly(self):
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            os.mkdir(os.path.join(d, "templates"))
            with open(os.path.join(d, "values.yaml"), "w") as f:
                f.write("a: 1\n")
            with open(os.path.join(d, "templates", "x.yaml"), "w") as f:
                f.write("v: {{ .Values.missing.key }}\n")
            with pytest.raises(KeyError):
                render_chart(d)


class TestCrdPrinterColumns:
    def test_columns_reference_conditions_the_controller_writes(self):
        """kubectl get provisioner surfaces Active/SolverHealthy — the
        jsonPaths must name the exact condition types the provisioning
        controller maintains (controllers/provisioning.py)."""
        import re

        for path in ("deploy/crds/karpenter.sh_provisioners.yaml",
                     "charts/karpenter-tpu/crds/karpenter.sh_provisioners.yaml"):
            with open(path) as f:
                src = f.read()
            assert "additionalPrinterColumns" in src, path
            types = set(re.findall(r'@\.type=="(\w+)"', src))
            assert types == {"Active", "SolverHealthy"}, (path, types)
            assert ".status.resources.cpu" in src
            assert ".status.resources.memory" in src
            # declaring printer columns replaces the apiserver's default
            # set — Age must be re-added explicitly or kubectl loses it
            assert ".metadata.creationTimestamp" in src
