"""The write-ahead intent journal (runtime/journal.py).

Covers the storage format (CRC framing, torn-tail tolerance, mid-segment
corruption, rotation, compaction-as-atomic-rewrite), the intent state
machines (monotonic advance, data-only notes, idempotent close), the
launch-nonce pre-stamp plumbing, and the kill-point catalog the
crash-restart soak (test_crash_recovery.py) iterates.
"""

import json
import os
import threading
import zlib

import pytest

from karpenter_tpu.chaos import inject
from karpenter_tpu.runtime import journal as jr
from karpenter_tpu.runtime.journal import (
    KILL_POINTS, MACHINES, IntentJournal, _decode_line,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    inject.uninstall()


def segments(d):
    return sorted(f for f in os.listdir(d) if f.endswith(".wal"))


def raw_lines(d):
    out = []
    for fn in segments(d):
        with open(os.path.join(d, fn), "rb") as f:
            out.extend(line for line in f.read().split(b"\n") if line)
    return out


class TestFraming:
    def test_decode_roundtrip(self):
        payload = json.dumps({"id": "x", "kind": "drain",
                              "phase": "open"}).encode()
        line = f"{zlib.crc32(payload):08x} ".encode() + payload
        assert _decode_line(line) == {"id": "x", "kind": "drain",
                                      "phase": "open"}

    def test_decode_rejects_garbage(self):
        payload = b'{"id":"x"}'
        good = f"{zlib.crc32(payload):08x} ".encode() + payload
        assert _decode_line(b"") is None
        assert _decode_line(b"short") is None
        assert _decode_line(b"zzzzzzzz " + payload) is None  # bad hex
        assert _decode_line(good[:-2]) is None               # torn payload
        assert _decode_line(good.replace(b'"x"', b'"y"')) is None  # bit flip
        # valid CRC over a non-object payload
        arr = b"[1,2]"
        assert _decode_line(f"{zlib.crc32(arr):08x} ".encode() + arr) is None

    def test_every_written_line_is_framed(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        iid = j.open_intent("drain", node="n1")
        j.advance(iid, "deleting")
        j.close(iid)
        lines = raw_lines(str(tmp_path))
        assert len(lines) == 3
        phases = [_decode_line(line)["phase"] for line in lines]
        assert phases == ["open", "deleting", "closed"]


class TestReplay:
    def test_restart_restores_open_intents(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        a = j.open_intent("fleet-launch", nonce="abc", quantity=2)
        b = j.open_intent("bind", node="n1", pods=["default/p1"])
        j.advance(b, "node-created")
        c = j.open_intent("drain", node="n2")
        j.close(c)
        j.close_journal()

        j2 = IntentJournal(str(tmp_path), fsync=False)
        live = j2.open_intents()
        assert set(live) == {a, b}
        assert live[a].phase == "open"
        assert live[a].data["nonce"] == "abc"
        assert live[b].phase == "node-created"
        assert live[b].data["pods"] == ["default/p1"]
        assert j2.stats()["torn_records"] == 0

    def test_torn_tail_tolerated(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        a = j.open_intent("drain", node="n1")
        j.advance(a, "deleting")
        j.close_journal()
        # crash mid-append: the final line loses its tail bytes
        path = os.path.join(str(tmp_path), segments(str(tmp_path))[-1])
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[:-7])

        j2 = IntentJournal(str(tmp_path), fsync=False)
        assert j2.stats()["torn_records"] == 1
        # the open record survived; the torn advance is simply not there
        assert j2.open_intents()[a].phase == "open"
        # appends go to a FRESH segment: the torn tail is never
        # appended after, so it stays the last line of ITS segment
        j2.advance(a, "deleting")
        assert len(segments(str(tmp_path))) == 2

    def test_mid_segment_corruption_skipped(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        a = j.open_intent("drain", node="n1")
        b = j.open_intent("drain", node="n2")
        j.advance(b, "deleting")
        j.close_journal()
        path = os.path.join(str(tmp_path), segments(str(tmp_path))[-1])
        with open(path, "rb") as f:
            lines = f.read().split(b"\n")
        lines[1] = b"xx" + lines[1][2:]  # corrupt b's open, keep the rest
        with open(path, "wb") as f:
            f.write(b"\n".join(lines))

        j2 = IntentJournal(str(tmp_path), fsync=False)
        assert j2.stats()["torn_records"] == 1
        live = j2.open_intents()
        assert live[a].phase == "open"
        # records are self-describing: the surviving advance still
        # reconstructs b (kind + phase) despite its torn open
        assert live[b].kind == "drain"
        assert live[b].phase == "deleting"

    def test_close_record_wins_over_corrupt_history(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        a = j.open_intent("drain", node="n1")
        j.close(a)
        j.close_journal()
        j2 = IntentJournal(str(tmp_path), fsync=False)
        assert j2.open_intents() == {}


class TestRotationAndCompaction:
    def test_rotation_at_segment_cap(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False,
                          segment_max_records=2)
        for _ in range(3):
            iid = j.open_intent("drain", node="n")
            j.close(iid)
        assert len(segments(str(tmp_path))) >= 3

    def test_compaction_keeps_only_open_intents(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False,
                          segment_max_records=2)
        keep = j.open_intent("fleet-launch", nonce="keep-me")
        for _ in range(5):
            iid = j.open_intent("drain", node="n")
            j.close(iid)
        before = len(raw_lines(str(tmp_path)))
        removed = j.compact()
        assert removed >= 1
        lines = raw_lines(str(tmp_path))
        assert len(lines) < before
        assert all(_decode_line(line)["id"] == keep for line in lines)
        # the compacted journal replays identically
        j.close_journal()
        j2 = IntentJournal(str(tmp_path), fsync=False)
        assert set(j2.open_intents()) == {keep}
        assert j2.open_intents()[keep].data["nonce"] == "keep-me"

    def test_compaction_of_all_closed_empties_the_dir(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        for _ in range(3):
            j.close(j.open_intent("drain", node="n"))
        j.compact()
        assert raw_lines(str(tmp_path)) == []
        j.close_journal()
        assert IntentJournal(str(tmp_path), fsync=False).open_intents() == {}

    def test_append_after_compaction_lands_in_new_segment(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        j.close(j.open_intent("drain", node="n"))
        j.compact()
        a = j.open_intent("drain", node="m")
        j.close_journal()
        j2 = IntentJournal(str(tmp_path), fsync=False)
        assert set(j2.open_intents()) == {a}


class TestStateMachines:
    def test_unknown_kind_rejected(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        with pytest.raises(ValueError):
            j.open_intent("teleport")

    def test_advance_validates_membership_and_order(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        iid = j.open_intent("bind", node="n1")
        with pytest.raises(ValueError):
            j.advance(iid, "launched")  # fleet-launch phase, not bind's
        with pytest.raises(ValueError):
            j.advance(iid, "open")      # no going back
        with pytest.raises(ValueError):
            j.advance(iid, "closed")    # terminal is close()'s job
        j.advance(iid, "bound")         # skipping node-created is legal
        with pytest.raises(ValueError):
            j.advance(iid, "node-created")  # monotonic
        with pytest.raises(KeyError):
            j.advance("no-such-intent", "bound")

    def test_note_grows_data_without_phase_change(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        iid = j.open_intent("gang-bind", gang="g", members=["default/a"])
        j.note(iid, created=["node-1"])
        j.note(iid, created=["node-1", "node-2"])
        intent = j.intent(iid)
        assert intent.phase == "open"
        assert intent.data["created"] == ["node-1", "node-2"]
        j.close_journal()
        restored = IntentJournal(str(tmp_path), fsync=False).intent(iid)
        assert restored.phase == "open"
        assert restored.data["created"] == ["node-1", "node-2"]

    def test_close_unknown_is_noop(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        j.close("never-opened")  # recovery and the happy path may race
        iid = j.open_intent("drain", node="n")
        j.close(iid, outcome="done")
        j.close(iid, outcome="again")  # double close: no-op, no record
        assert len(raw_lines(str(tmp_path))) == 2

    def test_covered_nonces(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        a = j.open_intent("fleet-launch", nonce="n-a")
        g = j.open_intent("gang-bind", gang="g", members=[])
        j.note(g, nonces=["n-g1", "n-g2"])
        b = j.open_intent("fleet-launch", nonce="n-b")
        j.open_intent("drain", node="x")
        assert j.covered_nonces() == {"n-a", "n-b", "n-g1", "n-g2"}
        j.close(a)
        j.close(g)
        assert j.covered_nonces() == {"n-b"}
        j.close(b)
        assert j.covered_nonces() == set()


class TestNoncePlumbing:
    def test_preassigned_nonce_nests_and_restores(self):
        assert jr.current_preassigned_nonce() is None
        with jr.preassigned_nonce("outer"):
            assert jr.current_preassigned_nonce() == "outer"
            with jr.preassigned_nonce("inner"):
                assert jr.current_preassigned_nonce() == "inner"
            assert jr.current_preassigned_nonce() == "outer"
        assert jr.current_preassigned_nonce() is None

    def test_preassigned_nonce_is_thread_local(self):
        seen = {}

        def peek():
            seen["other"] = jr.current_preassigned_nonce()

        with jr.preassigned_nonce("mine"):
            t = threading.Thread(target=peek)
            t.start()
            t.join()
        assert seen["other"] is None


class TestKillPoints:
    def test_catalog_shape(self):
        # pre + post per (kind, phase) across every machine
        assert len(KILL_POINTS) == 2 * sum(len(p) for p in MACHINES.values())
        assert "pre:fleet-launch:open" in KILL_POINTS
        assert "fleet-launch:open" in KILL_POINTS
        assert "gang-bind:unwinding" in KILL_POINTS
        assert "pre:node-delete:instance-deleted" in KILL_POINTS
        assert len(set(KILL_POINTS)) == len(KILL_POINTS)

    def test_pre_point_crashes_before_durability(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        inject.install(inject.FaultPlan(1, [
            inject.FaultSpec("journal", "pre:drain:open",
                             "crash-point", 1)], window=1))
        with pytest.raises(inject.SimulatedCrash) as e:
            j.open_intent("drain", node="n1")
        assert e.value.point == "pre:drain:open"
        inject.uninstall()
        j.close_journal()
        # nothing durable: the restarted journal has no trace of it
        assert IntentJournal(str(tmp_path), fsync=False).open_intents() == {}

    def test_post_point_crashes_after_durability(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        inject.install(inject.FaultPlan(1, [
            inject.FaultSpec("journal", "drain:open",
                             "crash-point", 1)], window=1))
        with pytest.raises(inject.SimulatedCrash):
            j.open_intent("drain", node="n1")
        inject.uninstall()
        j.close_journal()
        live = IntentJournal(str(tmp_path), fsync=False).open_intents()
        assert len(live) == 1
        intent = next(iter(live.values()))
        assert intent.kind == "drain" and intent.phase == "open"

    def test_simulated_crash_is_not_an_exception(self):
        # broad `except Exception` error handling must not survive a
        # kill point, exactly like a real SIGKILL
        assert not issubclass(inject.SimulatedCrash, Exception)
        assert issubclass(inject.SimulatedCrash, BaseException)

    def test_no_plan_is_free(self, tmp_path):
        j = IntentJournal(str(tmp_path), fsync=False)
        iid = j.open_intent("drain", node="n1")  # no raise, no plan
        j.close(iid)
