"""KubeApiClient against a live stub API server.

The stub speaks enough of the Kubernetes REST protocol (collections, items,
fieldSelector, binding/eviction subresources, chunked ?watch=true streams)
and is backed by KubeCore — so these tests exercise the real HTTP client,
the JSON codecs, and API-server semantics (404/409/conflict) end to end
over a socket.
"""

import json
import os
import threading
import time
import queue as queue_mod
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

import pytest

from karpenter_tpu.api.core import ConfigMap, Node, ObjectMeta, Pod, PodSpec
from karpenter_tpu.runtime.kubeclient import (
    KubeApiClient, ROUTES, _decode as wire_decode, _encode as wire_encode,
)
from karpenter_tpu.runtime.kubecore import (
    AlreadyExists, Conflict, InternalError, KubeCore, NotFound,
    TooManyRequests,
)
from tests.expectations import unschedulable_pod

PLURALS = {plural: kind for kind, (_, plural, _c) in ROUTES.items()}


class StubHandler(BaseHTTPRequestHandler):
    core: KubeCore = None
    protocol_version = "HTTP/1.1"
    # fault injection, mutated by tests mid-flight:
    #   watch_410_next: after the next streamed event, emit an ERROR Status
    #                   (code 410, reason Expired) and close — the real
    #                   apiserver's watch-cache-expiry signal
    #   throttle_429: serve this many 429+Retry-After responses (APF throttle)
    #   evict_429: eviction subresource answers 429 (PDB would be violated)
    behavior: dict = None

    def log_message(self, *a):
        pass

    def _send(self, code, body=b"", chunked=False):
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        if chunked:
            self.send_header("Transfer-Encoding", "chunked")
        else:
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _parse(self):
        split = urlsplit(self.path)
        parts = [p for p in split.path.split("/") if p]
        qs = parse_qs(split.query)
        # /api/v1/... or /apis/group/v1/...
        parts = parts[2:] if parts[0] == "api" else parts[3:]
        namespace = None
        if parts and parts[0] == "namespaces":
            namespace = parts[1]
            parts = parts[2:]
        kind = PLURALS.get(parts[0]) if parts else None
        name = parts[1] if len(parts) > 1 else None
        sub = parts[2] if len(parts) > 2 else None
        return kind, namespace, name, sub, qs

    def do_GET(self):
        kind, namespace, name, _, qs = self._parse()
        if self.behavior and self.behavior.get("throttle_429", 0) > 0:
            self.behavior["throttle_429"] -= 1
            self.send_response(429)
            self.send_header("Retry-After", "0")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if name:
            try:
                obj = self.core.get(kind, name, namespace or "default"
                                    if not ROUTES[kind][2] else "")
            except NotFound:
                return self._send(404, b"{}")
            return self._send(200, json.dumps(wire_encode(obj)).encode())
        field = None
        if "fieldSelector" in qs:
            fname, fval = qs["fieldSelector"][0].split("=", 1)
            field = (fname, fval)
        if qs.get("watch") == ["true"]:
            return self._watch(kind)
        if ("continue" in qs and self.behavior
                and self.behavior.pop("list_410_once", None)):
            # expired continue token (etcd compaction / token TTL): the
            # real apiserver answers 410 Gone mid-pagination
            return self._send(410, b'{"kind":"Status","code":410}')
        items = self.core.list(kind, namespace=namespace, field=field)
        if self.behavior:
            omit = self.behavior.pop("list_omit_once", None)
            if omit:
                # stale watch-cache LIST: the real apiserver may serve a
                # LIST from a cache that has not yet observed a recent
                # write — the object exists but is missing from this page
                items = [o for o in items if o.metadata.name != omit]
        if "labelSelector" in qs:
            # equality terms only — enough for the client's match_labels
            # (operator terms are covered by the serialization test)
            terms = [t for t in qs["labelSelector"][0].split(",") if "=" in t
                     and " in " not in t and " notin " not in t]
            pairs = [t.split("=", 1) for t in terms]
            items = [o for o in items
                     if all(o.metadata.labels.get(k) == v for k, v in pairs)]
        # real-apiserver chunking: limit/continue over a stable ordering
        # (the apiserver pages by etcd key order; name order is the analog)
        items.sort(key=lambda o: (o.metadata.namespace or "", o.metadata.name))
        limit = int(qs.get("limit", ["0"])[0] or 0)
        offset = int(qs.get("continue", ["0"])[0] or 0)
        meta = {"resourceVersion": "1"}
        if limit and offset + limit < len(items):
            page = items[offset:offset + limit]
            meta["continue"] = str(offset + limit)
        else:
            page = items[offset:]
        if self.behavior is not None:
            self.behavior["list_requests"] = (
                self.behavior.get("list_requests", 0) + 1)
        body = {"kind": f"{kind}List", "metadata": meta,
                "items": [wire_encode(o) for o in page]}
        self._send(200, json.dumps(body).encode())

    def _watch(self, kind):
        q = self.core.watch(kind)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        try:
            while True:
                try:
                    event = q.get(timeout=5.0)
                except queue_mod.Empty:
                    return
                line = json.dumps({
                    "type": event.type,
                    "object": wire_encode(event.obj),
                }).encode() + b"\n"
                self.wfile.write(line)
                self.wfile.flush()
                if self.behavior and self.behavior.pop("bookmark_next", None):
                    bm = json.dumps({
                        "type": "BOOKMARK",
                        "object": {"kind": "Pod", "metadata": {
                            "resourceVersion": "9999"}},
                    }).encode() + b"\n"
                    self.wfile.write(bm)
                    self.wfile.flush()
                if self.behavior and self.behavior.pop("watch_410_next", None):
                    err = json.dumps({
                        "type": "ERROR",
                        "object": {"kind": "Status", "code": 410,
                                   "reason": "Expired",
                                   "message": "too old resource version"},
                    }).encode() + b"\n"
                    self.wfile.write(err)
                    self.wfile.flush()
                    return
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            self.core.unwatch(q)

    def _body(self):
        return json.loads(self.rfile.read(int(self.headers["Content-Length"])))

    def do_POST(self):
        kind, namespace, name, sub, _ = self._parse()
        body = self._body()
        if sub == "binding":
            pod = self.core.get("Pod", name, namespace)
            try:
                self.core.bind_pod(pod, body["target"]["name"])
            except Conflict:
                return self._send(409, b"{}")
            return self._send(201, b"{}")
        if sub == "eviction":
            if self.behavior and self.behavior.get("evict_429"):
                self.send_response(429)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            try:
                self.core.evict_pod(name, namespace)
            except NotFound:
                return self._send(404, b"{}")
            except TooManyRequests:
                # PDB violation — real apiserver eviction REST semantics
                return self._send(
                    429, b'{"kind":"Status","code":429,'
                         b'"reason":"TooManyRequests"}')
            except InternalError:
                # >1 PDB matches: misconfiguration → 500
                return self._send(
                    500, b'{"kind":"Status","code":500,'
                         b'"message":"found more than one '
                         b'PodDisruptionBudget"}')
            return self._send(201, b"{}")
        obj = wire_decode(kind, body)
        try:
            created = self.core.create(obj)
        except AlreadyExists:
            return self._send(409, b"{}")
        self._send(201, json.dumps(wire_encode(created)).encode())

    def do_PUT(self):
        kind, namespace, name, sub, _ = self._parse()
        obj = wire_decode(kind, self._body())
        try:
            if kind == "Provisioner":
                # real-apiserver contract for a CRD with the status
                # subresource (deploy/crds/…yaml:20-21): the main PUT
                # IGNORES status changes; PUT …/status ignores everything
                # BUT status
                stored = self.core.get(kind, name, namespace or "default")
                if sub == "status":
                    incoming_status = obj.status
                    incoming_rv = obj.metadata.resource_version
                    obj = wire_decode(kind, wire_encode(stored))
                    obj.metadata.resource_version = incoming_rv
                    obj.status = incoming_status
                else:
                    obj.status = stored.status
            updated = self.core.update(obj)
        except Conflict:
            return self._send(409, b"{}")
        except NotFound:
            return self._send(404, b"{}")
        self._send(200, json.dumps(wire_encode(updated)).encode())

    def do_DELETE(self):
        kind, namespace, name, _, _ = self._parse()
        precondition_rv = None
        length = int(self.headers.get("Content-Length") or 0)
        if length:
            opts = json.loads(self.rfile.read(length))
            precondition_rv = (opts.get("preconditions") or {}).get(
                "resourceVersion")
        try:
            self.core.delete(kind, name, namespace or "default"
                             if not ROUTES[kind][2] else "",
                             precondition_rv=precondition_rv)
        except Conflict:
            return self._send(409, b'{"kind":"Status","code":409}')
        except NotFound:
            return self._send(404, b"{}")
        self._send(200, b"{}")


@pytest.fixture()
def api():
    core = KubeCore()
    handler = type("BoundStub", (StubHandler,), {"core": core, "behavior": {}})
    # a real apiserver accepts far more than the stdlib default backlog of
    # 5; the 64-worker selection plane overruns it (ECONNRESET under load)
    server_cls = type("Stub", (ThreadingHTTPServer,),
                      {"request_queue_size": 128, "daemon_threads": True})
    server = server_cls(("127.0.0.1", 0), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = KubeApiClient(f"http://127.0.0.1:{server.server_address[1]}")
    yield core, client, handler.behavior
    client.stop_watches()
    server.shutdown()


class TestCrud:
    def test_create_get_roundtrip(self, api):
        core, client, _ = api
        pod = unschedulable_pod(requests={"cpu": "250m", "memory": "64Mi"},
                                name="web-1")
        client.create(pod)
        got = client.get("Pod", "web-1")
        assert str(got.spec.containers[0].resources.requests["cpu"]) == "250m"
        assert got.status.conditions[0].reason == "Unschedulable"
        # visible to the backing store too (proves wire encoding, not echo)
        assert core.get("Pod", "web-1").metadata.name == "web-1"

    def test_not_found_and_conflict(self, api):
        core, client, _ = api
        with pytest.raises(NotFound):
            client.get("Pod", "missing")
        cm = ConfigMap(metadata=ObjectMeta(name="c"), data={"a": "1"})
        client.create(cm)
        with pytest.raises(AlreadyExists):
            client.create(cm)
        stale = client.get("ConfigMap", "c")
        stale.metadata.resource_version = 999  # wrong rv
        with pytest.raises(Conflict):
            client.update(stale)

    def test_patch_retries_conflicts(self, api):
        core, client, _ = api
        client.create(ConfigMap(metadata=ObjectMeta(name="c"), data={"n": "0"}))

        calls = {"n": 0}

        def bump(obj):
            if calls["n"] == 0:
                calls["n"] += 1
                # interleave a foreign write to force one 409
                core.patch("ConfigMap", "c", "default",
                           lambda o: o.data.update(foreign="x"))
            obj.data["n"] = "1"

        client.patch("ConfigMap", "c", "default", bump)
        final = client.get("ConfigMap", "c")
        assert final.data["n"] == "1" and final.data["foreign"] == "x"

    def test_field_selector_pods_on_node(self, api):
        core, client, _ = api
        for i, node in enumerate(["n1", "n1", "n2"]):
            core.create(Pod(metadata=ObjectMeta(name=f"p{i}"),
                            spec=PodSpec(node_name=node)))
        names = {p.metadata.name for p in client.pods_on_node("n1")}
        assert names == {"p0", "p1"}

    def test_cluster_scoped_node(self, api):
        core, client, _ = api
        client.create(Node(metadata=ObjectMeta(name="node-a", namespace="")))
        assert client.get("Node", "node-a", "").metadata.name == "node-a"
        client.delete("Node", "node-a", "")
        with pytest.raises(NotFound):
            client.get("Node", "node-a", "")

    def test_bind_and_evict(self, api):
        core, client, _ = api
        pod = unschedulable_pod(name="b1")
        client.create(pod)
        client.bind_pod(pod, "node-z")
        assert core.get("Pod", "b1").spec.node_name == "node-z"
        client.evict_pod("b1")
        with pytest.raises(NotFound):
            core.get("Pod", "b1")


class TestWatch:
    def test_watch_streams_events(self, api):
        core, client, _ = api
        core.create(Pod(metadata=ObjectMeta(name="pre")))  # before watch
        q = client.watch("Pod")
        seen = {}
        deadline = time.time() + 10
        core.create(Pod(metadata=ObjectMeta(name="post")))
        while time.time() < deadline and len(seen) < 2:
            try:
                ev = q.get(timeout=1.0)
            except Exception:
                continue
            seen[ev.obj.metadata.name] = ev.type
        assert seen.get("pre") == "ADDED"      # initial list replay
        assert seen.get("post") == "ADDED"     # streamed event


class TestControlPlaneOverTheWire:
    def test_full_provisioning_via_http_client(self, api):
        """The COMPLETE control plane (all controllers via main.build_manager,
        fake cloud provider) running against the API server over HTTP:
        provisioner + pending pods in → nodes created and pods bound, every
        read/write/watch crossing the wire through KubeApiClient."""
        core, client, _ = api
        from karpenter_tpu.config.options import Options
        from karpenter_tpu.main import build_manager
        from tests.expectations import make_provisioner

        options = Options(cluster_name="test", cluster_endpoint="https://test",
                          cloud_provider="fake",
                          batch_idle_seconds=0.05, batch_max_seconds=2.0,
                          solver_use_device=False)  # keep CI fast: host solver
        manager = build_manager(client, options)
        manager.start()
        try:
            client.create(make_provisioner())
            pods = [unschedulable_pod(name=f"wire-{i}") for i in range(6)]
            for p in pods:
                client.create(p)
            deadline = time.time() + 60  # single-core CI: full stack is slow
            while time.time() < deadline:
                bound = [client.get("Pod", p.metadata.name).spec.node_name
                         for p in pods]
                if all(bound):
                    break
                time.sleep(0.25)
            assert all(client.get("Pod", p.metadata.name).spec.node_name
                       for p in pods), "pods were not bound over the wire"
            nodes = client.list("Node", namespace=None)
            assert nodes, "no nodes created"
            from karpenter_tpu.api import wellknown
            assert any(wellknown.TERMINATION_FINALIZER in n.metadata.finalizers
                       for n in nodes)
        finally:
            manager.stop()
            client.stop_watches()

    def test_wire_throughput_1k_pods(self, api):
        """Load over the WIRE (the bench's 10k-pod config runs against
        kubecore; this pins the HTTP path at a smaller scale): 1,000
        unschedulable pods through watch → selection → batcher → solve →
        bind, every operation crossing the stub apiserver."""
        core, client, _ = api
        from karpenter_tpu.config.options import Options
        from karpenter_tpu.main import build_manager
        from tests.expectations import make_provisioner

        options = Options(cluster_name="test", cluster_endpoint="https://test",
                          cloud_provider="fake",
                          batch_idle_seconds=0.2, batch_max_seconds=3.0,
                          solver_use_device=False)
        manager = build_manager(client, options)
        manager.start()
        n = 1_000
        try:
            client.create(make_provisioner())
            t0 = time.time()
            for i in range(n):
                client.create(unschedulable_pod(
                    requests={"cpu": f"{100 + (i % 8) * 250}m",
                              "memory": f"{64 * (1 + i % 4)}Mi"},
                    name=f"load-{i}"))
            deadline = time.time() + 120
            bound = 0
            while time.time() < deadline:
                bound = sum(1 for name, node in core.scan(
                    "Pod", lambda p: (p.metadata.name, p.spec.node_name))
                    if node)
                if bound == n:
                    break
                time.sleep(0.25)
            elapsed = time.time() - t0
            assert bound == n, f"only {bound}/{n} pods bound over the wire"
            rate = n / elapsed
            print(f"\nwire throughput: {n} pods bound in {elapsed:.1f}s "
                  f"({rate:.0f} pods/s over HTTP)")
            # floor, not a target: the stub server, client, controllers AND
            # solver share one GIL here — the kubecore bench (config 7)
            # carries the real throughput number (~450 pods/s); this pins
            # that the wire plane converges completely under load. The
            # timing floor only holds when this process has the machine to
            # itself: on a loaded CI host (1-min loadavg >= cores) the
            # convergence assertion above still ran, but the rate is noise.
            from tests.expectations import host_loaded

            if not host_loaded("wire rate floor"):
                assert rate > 8, (
                    f"wire control plane too slow: {rate:.0f} pods/s")
        finally:
            manager.stop()
            client.stop_watches()


class TestRealServerSemantics:
    def test_update_strips_finalizer_over_the_wire(self, api):
        """Owned-field removal must round-trip (termination's finalizer
        strip is the deprovisioning linchpin)."""
        core, client, _ = api
        core.create(Node(metadata=ObjectMeta(
            name="nx", namespace="", finalizers=["karpenter.sh/termination"])))
        got = client.get("Node", "nx", "")
        got.metadata.finalizers = []   # owned-field removal
        got.metadata.labels["added"] = "yes"
        client.update(got)
        stored = core.get("Node", "nx", "")
        assert stored.metadata.finalizers == []          # removal applied
        assert stored.metadata.labels["added"] == "yes"  # addition applied

    def test_merge_preserves_unmodeled_server_fields(self):
        """The read-merge-write overlay: server-owned JSON the codec does
        not model (podCIDR, kubelet conditions, defaulted fields) survives,
        while owned empties (finalizers: []) still express removal."""
        from karpenter_tpu.api.codec_core import node_to
        from karpenter_tpu.runtime.kubeclient import _merge

        raw = {
            "metadata": {"name": "nx", "finalizers": ["karpenter.sh/termination"],
                         "managedFields": [{"manager": "kubelet"}]},
            "spec": {"podCIDR": "10.1.0.0/24",
                     "taints": [{"key": "old", "effect": "NoSchedule"}]},
            "status": {"nodeInfo": {"kubeletVersion": "v1.29"}},
        }
        node = Node(metadata=ObjectMeta(name="nx", namespace=""))  # no finalizers
        merged = _merge(raw, node_to(node))
        assert merged["spec"]["podCIDR"] == "10.1.0.0/24"          # preserved
        assert merged["metadata"]["managedFields"]                  # preserved
        assert merged["status"]["nodeInfo"]["kubeletVersion"] == "v1.29"
        assert merged["metadata"]["finalizers"] == []               # removed
        assert merged["spec"]["taints"] == []                       # owned: replaced

    def test_label_selector_operator_serialization(self, api):
        """Exists → bare key, DoesNotExist → !key, NotIn → notin (...) —
        'app notin ()' for Exists would be a 400 on a real server."""
        from urllib.parse import parse_qs, urlsplit

        from karpenter_tpu.api.core import LabelSelector, NodeSelectorRequirement

        _, client, _b = api
        seen = {}
        original = client._request

        def capture(method, path, body=None, **kw):
            seen["path"] = path
            return {"items": []}

        client._request = capture
        try:
            client.list("Pod", namespace=None, label_selector=LabelSelector(
                match_labels={"team": "ml"},
                match_expressions=[
                    NodeSelectorRequirement(key="app", operator="Exists"),
                    NodeSelectorRequirement(key="gone", operator="DoesNotExist"),
                    NodeSelectorRequirement(key="zone", operator="NotIn",
                                            values=["z1"]),
                ]))
        finally:
            client._request = original
        sel = parse_qs(urlsplit(seen["path"]).query)["labelSelector"][0]
        assert sel == "team=ml,app,!gone,zone notin (z1)"

    def test_unwatch_stops_thread(self, api):
        """unwatch() closes the live streaming connection, so the backing
        thread exits IMMEDIATELY — no event traffic needed to nudge it out
        of its blocking read, no 300 s socket-timeout wait."""
        core, client, _ = api
        q = client.watch("Pod")
        core.create(Pod(metadata=ObjectMeta(name="settle")))
        q.get(timeout=10.0)  # stream is established and delivering
        threads_before = list(client._watch_threads)  # only THIS client's
        assert threads_before and all(t.is_alive() for t in threads_before)
        client.unwatch(q)
        deadline = time.time() + 5
        while time.time() < deadline and any(t.is_alive() for t in threads_before):
            time.sleep(0.05)  # deliberately NO pod creates: no nudging
        stuck = [t for t in threads_before if t.is_alive()]
        if stuck:
            import sys as _sys
            import traceback as _tb

            frames = _sys._current_frames()
            dumps = "\n".join(
                "".join(_tb.format_stack(frames[t.ident]))
                for t in stuck if t.ident in frames)
            raise AssertionError(f"watch thread(s) still alive:\n{dumps}")

    def test_watch_410_resync_loses_no_events(self, api):
        """The apiserver's most common watch failure: the stream dies with
        ERROR Status{code:410, reason:Expired}. The client must re-list and
        re-watch — events created after the expiry must still arrive, and
        the re-list replay proves the resync actually happened."""
        core, client, behavior = api
        core.create(Pod(metadata=ObjectMeta(name="before")))
        q = client.watch("Pod")
        ev = q.get(timeout=10.0)
        assert ev.obj.metadata.name == "before"  # initial list replay

        # arm the fault: the next streamed event is followed by ERROR 410
        behavior["watch_410_next"] = True
        core.create(Pod(metadata=ObjectMeta(name="trigger")))

        # after the forced expiry, a new object must still be observed
        seen = {}
        deadline = time.time() + 15
        created_after = False
        while time.time() < deadline:
            if not created_after and behavior.get("watch_410_next") is None:
                # fault has fired (stub popped the flag) — now create the
                # post-expiry object the resynced watch must deliver
                core.create(Pod(metadata=ObjectMeta(name="after-410")))
                created_after = True
            try:
                ev = q.get(timeout=0.5)
            except queue_mod.Empty:
                continue
            seen[ev.obj.metadata.name] = seen.get(ev.obj.metadata.name, 0) + 1
            # exit only once BOTH proofs are in: the post-expiry object
            # arrived AND the re-list replay was observed (replay order is
            # name-sorted — pagination — so after-410 can arrive first)
            if "after-410" in seen and seen.get("before", 0) >= 2:
                break
        assert "after-410" in seen, f"event lost across 410 resync: {seen}"
        # the resync re-list replays pre-existing objects as ADDED again
        assert seen.get("before", 0) >= 2, f"no re-list replay observed: {seen}"

    def test_429_outside_eviction_retries_not_conflict(self, api):
        """APF throttling (429 on a plain GET) is retried in place after
        Retry-After — it must NOT surface as an optimistic-concurrency
        Conflict (which would make patch() spin on re-reads)."""
        core, client, behavior = api
        core.create(ConfigMap(metadata=ObjectMeta(name="cm"), data={"k": "v"}))
        behavior["throttle_429"] = 1
        got = client.get("ConfigMap", "cm")  # retries through the 429
        assert got.data["k"] == "v"
        assert behavior["throttle_429"] == 0  # the throttle was actually hit

    def test_429_on_eviction_is_typed_pdb_violation(self, api):
        """On the eviction subresource 429 means 'PDB would be violated' —
        typed TooManyRequests so the eviction queue mirrors the reference's
        distinct handling (eviction.go:98-101)."""
        core, client, behavior = api
        core.create(Pod(metadata=ObjectMeta(name="guarded")))
        behavior["evict_429"] = True
        with pytest.raises(TooManyRequests):
            client.evict_pod("guarded")

    def test_eviction_pdb_semantics_over_the_wire(self, api):
        """PDB-aware eviction END TO END: the stub consults real
        PodDisruptionBudget objects via kubecore's eviction handler —
        violation → 429 TooManyRequests, two matching budgets → 500
        InternalError ('found more than one PodDisruptionBudget'),
        headroom → eviction succeeds. Contract: the real apiserver's
        eviction REST handler."""
        from karpenter_tpu.api.core import LabelSelector, PodDisruptionBudget

        core, client, behavior = api
        for i in range(2):
            pod = Pod(metadata=ObjectMeta(name=f"web-{i}",
                                          labels={"app": "web"}))
            pod.spec.node_name = "n1"
            core.create(pod)
        core.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="web-pdb"),
            selector=LabelSelector(match_labels={"app": "web"}),
            min_available=2))
        with pytest.raises(TooManyRequests):
            client.evict_pod("web-0")
        assert core.get("Pod", "web-0")  # still there

        # a second overlapping budget → misconfiguration → 500
        core.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="web-pdb-2"),
            selector=LabelSelector(match_labels={"app": "web"}),
            min_available=1))
        with pytest.raises(InternalError):
            client.evict_pod("web-0")

        # drop to one budget with headroom → eviction succeeds
        core.delete("PodDisruptionBudget", "web-pdb", "default")
        pod = Pod(metadata=ObjectMeta(name="web-2", labels={"app": "web"}))
        pod.spec.node_name = "n1"
        core.create(pod)
        client.evict_pod("web-0")
        with pytest.raises(NotFound):
            core.get("Pod", "web-0")

    def test_stale_list_converges_via_watch_replay(self, api):
        """List-newer-than-watch-cache contract (r5 tier): a LIST served
        from a stale watch cache omits a recent write; the client's
        informer must still converge because the subsequent watch stream
        replays/streams the missed object — a stale LIST is a snapshot,
        never a tombstone."""
        core, client, behavior = api
        core.create(ConfigMap(metadata=ObjectMeta(name="fresh"),
                              data={"k": "v"}))
        behavior["list_omit_once"] = "fresh"  # the feeder's LIST is stale
        q = client.watch("ConfigMap")
        try:
            deadline = time.time() + 10
            seen = False
            while time.time() < deadline and not seen:
                try:
                    ev = q.get(timeout=0.5)
                except queue_mod.Empty:
                    continue
                seen = ev.obj.metadata.name == "fresh"
            assert seen, "object missing from the stale LIST never arrived"
            # and the informer read path serves it
            assert client.get("ConfigMap", "fresh").data["k"] == "v"
        finally:
            client.stop_watches()

    def test_delete_preconditions_over_the_wire(self, api):
        """DELETE with preconditions.resourceVersion: a stale precondition
        conflicts (409) and leaves the object; the live one deletes."""
        core, client, behavior = api
        cm = core.create(ConfigMap(metadata=ObjectMeta(name="pc"),
                                   data={"k": "1"}))
        stale_rv = cm.metadata.resource_version
        core.patch("ConfigMap", "pc", "default",
                   lambda o: o.data.update({"k": "2"}))
        with pytest.raises(Conflict):
            client.delete("ConfigMap", "pc", precondition_rv=stale_rv)
        live = core.get("ConfigMap", "pc")
        assert live.data["k"] == "2"
        client.delete("ConfigMap", "pc",
                      precondition_rv=live.metadata.resource_version)
        with pytest.raises(NotFound):
            core.get("ConfigMap", "pc")


class TestGraceCodec:
    def test_grace_zero_round_trips(self):
        from karpenter_tpu.api.codec_core import pod_from, pod_to

        obj = {"metadata": {"name": "fast"},
               "spec": {"terminationGracePeriodSeconds": 0,
                        "containers": [{"name": "app", "resources": {}}]}}
        p = pod_from(obj)
        assert p.spec.termination_grace_period_seconds == 0  # not coerced to 30
        assert pod_to(p)["spec"]["terminationGracePeriodSeconds"] == 0
        p300 = pod_from({"metadata": {"name": "slow"},
                         "spec": {"terminationGracePeriodSeconds": 300}})
        assert pod_from(pod_to(p300)).spec.termination_grace_period_seconds == 300


class TestInformerReadCache:
    """The watch-fed read cache (controller-runtime cached-client analog):
    reads for watched kinds must come from local state, misses fall
    through live, the single feeder owns all writes, and losing the feeder
    disables serving."""

    def _wait_cached(self, client, kind, timeout=5.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            with client._cache_lock:
                if kind in client._cached_kinds:
                    return
            time.sleep(0.02)
        raise AssertionError(f"{kind} never became cache-served")

    def test_get_served_locally_after_watch(self, api):
        core, client, _ = api
        core.create(unschedulable_pod(name="cached-1"))
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        calls = {"n": 0}
        real = client._get_live

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        client._get_live = counting
        try:
            got = client.get("Pod", "cached-1")
            assert got.metadata.name == "cached-1"
            assert calls["n"] == 0  # served from cache, zero HTTP
            # miss falls through live
            try:
                client.get("Pod", "does-not-exist")
            except NotFound:
                pass
            assert calls["n"] == 1
        finally:
            client._get_live = real
            client.unwatch(q)

    def test_watch_events_update_cache(self, api):
        core, client, _ = api
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        core.create(unschedulable_pod(name="late-pod"))
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                if client.read("Pod", "late-pod", "default",
                               lambda p: p.metadata.name) == "late-pod":
                    break
            except NotFound:
                pass
            time.sleep(0.02)
        core.delete("Pod", "late-pod")
        deadline = time.time() + 5
        gone = False
        while time.time() < deadline:
            with client._cache_lock:
                gone = ("Pod", "default", "late-pod") not in client._read_cache
            if gone:
                break
            time.sleep(0.02)
        assert gone, "DELETED event did not evict the cache entry"
        client.unwatch(q)

    def test_unwatch_feeder_disables_serving(self, api):
        core, client, _ = api
        core.create(unschedulable_pod(name="p1"))
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        client.unwatch(q)
        with client._cache_lock:
            assert "Pod" not in client._cached_kinds
            assert not any(k[0] == "Pod" for k in client._read_cache)

    def test_cached_list_filters(self, api):
        core, client, _ = api
        pod = unschedulable_pod(name="labeled")
        pod.metadata.labels["team"] = "a"
        core.create(pod)
        core.create(unschedulable_pod(name="other"))
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        from karpenter_tpu.api.core import LabelSelector

        got = client.list("Pod", label_selector=LabelSelector(
            match_labels={"team": "a"}))
        assert [p.metadata.name for p in got] == ["labeled"]
        client.unwatch(q)

    def test_stale_feeder_falls_through_live(self, api):
        """A kind whose feeder stream has been down past the staleness
        bound must stop serving cached reads (advisor finding r3: a
        partitioned watch served ever-staler objects with no resync)."""
        core, client, _ = api
        core.create(unschedulable_pod(name="stale-1"))
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        calls = {"n": 0}
        real = client._get_live

        def counting(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        client._get_live = counting
        try:
            client.get("Pod", "stale-1")
            assert calls["n"] == 0  # healthy feeder: cache serves
            # feeder down for longer than the bound → reads go live
            with client._cache_lock:
                client._cache_down_since["Pod"] = (
                    time.monotonic() - client.cache_staleness_s - 1.0)
            client.get("Pod", "stale-1")
            assert calls["n"] == 1
            assert client._cache_list("Pod", None, None, None) is None
            # a fresh LIST snapshot (reconnect) restores serving
            with client._cache_lock:
                qid = client._cache_feeder["Pod"]
            client._cache_replace_kind(
                "Pod", [core.get("Pod", "stale-1")], qid)
            client.get("Pod", "stale-1")
            assert calls["n"] == 1  # cache serves again
        finally:
            client._get_live = real
            client.unwatch(q)

    def test_severed_stream_starts_staleness_clock(self, api):
        """Killing the live stream socket (transport partition) must mark
        the feeder down so the staleness clock is running."""
        core, client, _ = api
        core.create(unschedulable_pod(name="sever-1"))
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        # sever the transport out from under the stream thread; the loop
        # will mark the feeder down, then reconnect and re-list
        entry = client._watch_conns.get(id(q))
        assert entry is not None
        client._sever(entry)
        deadline = time.time() + 5.0
        marked = False
        while time.time() < deadline and not marked:
            with client._cache_lock:
                # either the down-clock is (or was) running, or the
                # reconnect already landed a fresh list — both prove the
                # transition happened; what can't happen is an untracked
                # stale stream. Catch the transient directly:
                marked = "Pod" in client._cache_down_since
            if not marked:
                time.sleep(0.005)
        # reconnect eventually restores serving with a fresh snapshot
        deadline = time.time() + 5.0
        while time.time() < deadline:
            with client._cache_lock:
                if ("Pod" in client._cached_kinds
                        and "Pod" not in client._cache_down_since):
                    break
            time.sleep(0.02)
        with client._cache_lock:
            assert "Pod" in client._cached_kinds
            assert "Pod" not in client._cache_down_since
        client.unwatch(q)

    def test_write_path_stays_live(self, api):
        core, client, _ = api
        core.create(unschedulable_pod(name="patched"))
        q = client.watch("Pod")
        self._wait_cached(client, "Pod")
        # patch must read LIVE (a stale cached object would re-conflict)
        client.patch("Pod", "patched", "default",
                     lambda p: p.metadata.annotations.update({"x": "y"}))
        stored = core.get("Pod", "patched")
        assert stored.metadata.annotations["x"] == "y"
        client.unwatch(q)


class TestListPagination:
    """Chunked LISTs (limit/continue): the reflector default. One giant
    response for a 50k-object collection is where big-cluster clients
    fall over; the client must follow continue tokens transparently."""

    def test_list_follows_continue_tokens(self, api):
        core, client, behavior = api
        for i in range(7):
            core.create(unschedulable_pod(name=f"page-{i}"))
        client.list_page_size = 3
        behavior["list_requests"] = 0
        pods = client.list("Pod")
        assert sorted(p.metadata.name for p in pods) == [
            f"page-{i}" for i in range(7)]
        assert behavior["list_requests"] == 3  # 3 + 3 + 1

    def test_watch_relist_paginates(self, api):
        core, client, behavior = api
        for i in range(5):
            core.create(unschedulable_pod(name=f"wp-{i}"))
        client.list_page_size = 2
        q = client.watch("Pod")
        seen = set()
        deadline = time.time() + 5.0
        while time.time() < deadline and len(seen) < 5:
            ev = q.get(timeout=5.0)
            seen.add(ev.obj.metadata.name)
        assert seen == {f"wp-{i}" for i in range(5)}
        # the watch-fed cache holds the full paginated snapshot
        assert len(client.list("Pod")) == 5
        client.unwatch(q)

    def test_expired_continue_token_restarts_list(self, api):
        """A 410 on a continue token mid-pagination (compaction/TTL) must
        restart the list transparently — before pagination this failure
        mode could not exist, and no list() caller handles it."""
        core, client, behavior = api
        for i in range(7):
            core.create(unschedulable_pod(name=f"exp-{i}"))
        client.list_page_size = 3
        behavior["list_410_once"] = True
        pods = client.list("Pod")
        assert sorted(p.metadata.name for p in pods) == [
            f"exp-{i}" for i in range(7)]
        assert "list_410_once" not in behavior  # the fault actually fired

    def test_selector_filters_compose_with_pagination(self, api):
        core, client, behavior = api
        from karpenter_tpu.api.core import LabelSelector

        for i in range(6):
            pod = unschedulable_pod(name=f"sel-{i}")
            pod.metadata.labels["team"] = "a" if i % 2 == 0 else "b"
            core.create(pod)
        client.list_page_size = 2
        got = client.list("Pod", label_selector=LabelSelector(
            match_labels={"team": "a"}))
        assert sorted(p.metadata.name for p in got) == [
            "sel-0", "sel-2", "sel-4"]


class TestStatusSubresourceAndBookmarks:
    """Real-apiserver contracts the in-memory plane can't see: the CRD's
    status subresource (main PUT drops status; /status PUT drops the rest)
    and BOOKMARK watch events."""

    def test_status_subresource_contract_over_the_wire(self, api):
        """The CRD declares the status subresource (deploy/crds/…:20-21),
        so against a REAL apiserver a main-resource PUT silently drops
        status changes — the client must write status via …/status or the
        counter/conditions writes never persist (they would re-write every
        reconcile, a status-write/watch-event loop)."""
        from karpenter_tpu.api.provisioner import (
            Provisioner, get_condition, set_condition,
        )

        core, client, _ = api
        prov = Provisioner()
        prov.metadata.name = "sub"
        core.create(prov)

        # client.patch mutating ONLY status → persists via the subresource
        def add_cond(p):
            set_condition(p.status.conditions, "Active", "True",
                          "WorkerRunning", now=1_700_000_000.0)

        client.patch("Provisioner", "sub", "default", add_cond)
        stored = core.get("Provisioner", "sub")
        cond = get_condition(stored.status.conditions, "Active")
        assert cond is not None and cond.status == "True"

        # a main-resource PUT carrying a status mutation must NOT change
        # status (real-apiserver semantics, modeled by the stub)
        live = client.get("Provisioner", "sub")
        live.status.conditions = []
        live.spec.ttl_seconds_after_empty = 30
        # drive the raw main PUT (bypassing update()'s subresource split)
        raw = client._request("GET", client._item("Provisioner", "sub",
                                                  "default"))
        raw["spec"]["ttlSecondsAfterEmpty"] = 60
        raw["status"] = {}  # attempt to clear status via the main resource
        client._request("PUT", client._item("Provisioner", "sub", "default"),
                        raw)
        stored = core.get("Provisioner", "sub")
        assert stored.spec.ttl_seconds_after_empty == 60  # spec applied
        cond = get_condition(stored.status.conditions, "Active")
        assert cond is not None, (
            "main-resource PUT cleared status — the stub no longer models "
            "the real subresource contract")

    def test_status_put_ignores_spec_changes(self, api):
        """PUT …/status applies status only (the inverse contract)."""
        from karpenter_tpu.api.provisioner import Provisioner

        core, client, _ = api
        prov = Provisioner()
        prov.metadata.name = "sub2"
        prov.spec.ttl_seconds_after_empty = 10
        core.create(prov)
        item = client._item("Provisioner", "sub2", "default")
        raw = client._request("GET", item)
        raw["spec"]["ttlSecondsAfterEmpty"] = 999
        raw["status"] = {"resources": {"cpu": "4"}}
        client._request("PUT", item + "/status", raw)
        stored = core.get("Provisioner", "sub2")
        assert stored.spec.ttl_seconds_after_empty == 10  # spec untouched
        assert str(stored.status.resources["cpu"]) == "4"

    def test_bookmark_events_are_swallowed(self, api):
        """A real apiserver sends BOOKMARK events when asked
        (allowWatchBookmarks — this client asks): they are rv checkpoints,
        not object events, and must neither reach consumers (an empty-name
        reconcile) nor touch the cache."""
        core, client, behavior = api

        def drain_to(name, deadline_s=5.0):
            """Deliver events until `name` appears; any event with an empty
            name is a leaked bookmark shell (the failure being tested).
            Duplicate ADDEDs from list replay are expected (level-triggered
            consumers tolerate them)."""
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                ev = q.get(timeout=deadline_s)
                assert ev.obj.metadata.name, "bookmark shell reached consumer"
                if ev.obj.metadata.name == name:
                    return ev
            raise AssertionError(f"{name} never delivered")

        q = client.watch("Pod")
        core.create(unschedulable_pod(name="bm-1"))
        drain_to("bm-1")
        behavior["bookmark_next"] = True
        core.create(unschedulable_pod(name="bm-2"))  # bookmark follows this
        drain_to("bm-2")
        # the bookmark between bm-2 and bm-3 must be swallowed and the
        # stream keep flowing
        core.create(unschedulable_pod(name="bm-3"))
        drain_to("bm-3")
        with client._cache_lock:
            assert ("Pod", "default", "") not in client._read_cache
        client.unwatch(q)


class TestProvisionerWireEncode:
    def test_status_conditions_and_resources_both_survive_encode(self):
        """_encode must not override the codec's status emission: dropping
        conditions on the wire turns the condition refresh into a
        self-sustaining status-write/watch-event loop (review finding r4)."""
        from karpenter_tpu.api.provisioner import Provisioner, set_condition
        from karpenter_tpu.runtime.kubeclient import _encode
        from karpenter_tpu.utils.resources import parse_resource_list

        p = Provisioner()
        p.metadata.name = "wire"
        p.status.resources = parse_resource_list({"cpu": "16", "memory": "64Gi"})
        set_condition(p.status.conditions, "Active", "True", "WorkerRunning",
                      now=1_700_000_000.0)
        manifest = _encode(p)
        st = manifest["status"]
        assert st["resources"] == {"cpu": "16", "memory": "64Gi"}
        assert st["conditions"][0]["type"] == "Active"
        assert st["conditions"][0]["lastTransitionTime"].endswith("Z")

    def test_malformed_last_transition_time_decodes_leniently(self):
        from karpenter_tpu.api.codec import provisioner_from_manifest

        m = {"apiVersion": "karpenter.sh/v1alpha5", "kind": "Provisioner",
             "metadata": {"name": "x"},
             "status": {"conditions": [
                 {"type": "Active", "status": "True",
                  "lastTransitionTime": 1234},      # number, not string
                 {"type": "B", "status": "True",
                  "lastTransitionTime": "garbage"},  # unparseable
             ]}}
        p = provisioner_from_manifest(m)  # must not raise (webhook path)
        assert p.status.conditions[0].last_transition_time is None
        assert p.status.conditions[1].last_transition_time is None


class TestWatchRelistMetric:
    """karpenter_watch_relist_total: every relist-and-reconcile forced by
    a watch gap is counted by reason (ISSUE 17 satellite — the blind-
    resume risk made observable)."""

    def _totals(self, kind):
        from karpenter_tpu.metrics.recovery import WATCH_RELIST_TOTAL

        out = {"expired": 0.0, "reconnect": 0.0}
        for labels, v in WATCH_RELIST_TOTAL.collect().items():
            d = dict(labels)
            if d.get("kind") == kind:
                out[d.get("reason")] = v
        return out

    def test_initial_list_is_not_a_relist(self, api):
        core, client, _ = api
        before = self._totals("Node")
        q = client.watch("Node")
        core.create(Node(metadata=ObjectMeta(name="n0")))
        ev = q.get(timeout=10.0)
        assert ev.obj.metadata.name == "n0"
        assert self._totals("Node") == before  # first snapshot: no gap

    def test_410_expiry_counts_an_expired_relist(self, api):
        core, client, behavior = api
        before = self._totals("Pod")
        core.create(Pod(metadata=ObjectMeta(name="seed")))
        q = client.watch("Pod")
        q.get(timeout=10.0)  # initial replay

        behavior["watch_410_next"] = True
        core.create(Pod(metadata=ObjectMeta(name="trigger")))
        # the resync re-list replays "seed" as ADDED a second time; once
        # observed, the expired relist must have been counted
        seen = {}
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                ev = q.get(timeout=0.5)
            except queue_mod.Empty:
                continue
            seen[ev.obj.metadata.name] = seen.get(ev.obj.metadata.name,
                                                  0) + 1
            if seen.get("seed", 0) >= 2:
                break
        assert seen.get("seed", 0) >= 2, f"no re-list replay: {seen}"
        after = self._totals("Pod")
        assert after["expired"] >= before["expired"] + 1, (before, after)
