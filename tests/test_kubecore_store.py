"""Differential + concurrency suite for the striped kube store.

The striped, by-kind-indexed KubeCore (runtime/kubecore.py) must be
semantically IDENTICAL to the pre-striping single-lock layout, which
survives as :class:`NaiveKubeCore`. Three legs:

1. **Seeded serialized traces** (seeds 1/7/42): a few hundred randomized
   ops — create/get/read/list/scan/update/patch/delete (with and without
   preconditions)/bind/bulk-bind/evict with PDBs — applied to both stores
   in the same order; after EVERY op, op outcome (value or exception
   type) and full store state must match exactly (the clock is pinned, so
   even timestamps and resourceVersions compare equal).
2. **Concurrent interleavings**: bind/evict/create threads race on the
   striped store; the op set is chosen so the final state is
   order-independent, and it must equal the naive store's serial result
   modulo resourceVersion ordering. A PDB leg asserts the atomic
   check-then-delete bound holds under concurrent evictions, and a mixed
   cross-stripe leg (evict + watch(None) + new-kind creates) must simply
   finish — the lock-order deadlock smoke.
3. **Watch-under-striping semantics**: a watcher registered mid-write
   sees pre- or post-state, never a torn object; registration never
   loses an event; ``_watchers`` is copy-on-write.
"""

from __future__ import annotations

import random
import threading

import pytest

from karpenter_tpu.api.core import (
    LabelSelector, Node, ObjectMeta, Pod, PodDisruptionBudget, PodSpec,
)
from karpenter_tpu.runtime.kubecore import (
    ApiError, KubeCore, NaiveKubeCore, MetaObj,
)
from karpenter_tpu.utils import clock
from karpenter_tpu.utils.fastcopy import deep_copy

KINDS = ("Pod", "Node", "PodDisruptionBudget")
NAMESPACES = ("default", "team-a")
POD_NAMES = [f"pod-{i}" for i in range(16)]
NODE_NAMES = [f"node-{i}" for i in range(5)]
PDB_NAMES = [f"pdb-{i}" for i in range(3)]


def _pod(name, ns, labels=None, finalizers=None):
    return Pod(metadata=ObjectMeta(name=name, namespace=ns,
                                   labels=dict(labels or {}),
                                   finalizers=list(finalizers or [])),
               spec=PodSpec())


def _node(name):
    return Node(metadata=ObjectMeta(name=name, namespace="default"))


def _pdb(name, ns, app, min_available=None, max_unavailable=None):
    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name, namespace=ns),
        selector=LabelSelector(match_labels={"app": app}),
        min_available=min_available, max_unavailable=max_unavailable)


def _dump(store):
    """Full observable state, exactly comparable between layouts (the
    clock is pinned, so timestamps agree; RVs agree for serialized
    traces)."""
    state = {}
    for kind in KINDS:
        objs = sorted(store.list(kind),
                      key=lambda o: (o.metadata.namespace, o.metadata.name))
        state[kind] = objs
    return state


def _norm_result(value):
    """Normalize an op's return for comparison: API objects reduce to
    their identifying fields, lists are compared order-insensitively
    (list/scan iteration order is a layout artifact, not API contract)."""
    if isinstance(value, list):
        return sorted(_norm_result(v) for v in value)
    if hasattr(value, "metadata"):
        return (value.kind, value.metadata.namespace, value.metadata.name,
                value.metadata.resource_version,
                value.metadata.deletion_timestamp,
                tuple(sorted(value.metadata.labels.items())))
    return value


def _trace(rng: random.Random, n_ops: int):
    """A seeded op trace: descriptors only (no store references), so the
    identical trace applies to both layouts."""
    ops = []
    for i in range(n_ops):
        kind = rng.choice(
            ["create_pod", "create_pod", "create_node", "create_pdb",
             "get", "read", "list", "scan", "pods_on_node",
             "update", "patch", "delete", "delete_precond",
             "bind", "bulk_bind", "evict"])
        ns = rng.choice(NAMESPACES)
        pod = rng.choice(POD_NAMES)
        if kind == "create_pod":
            ops.append((kind, pod, ns, f"app-{rng.randrange(3)}",
                        rng.random() < 0.2))  # 20%: with a finalizer
        elif kind == "create_node":
            ops.append((kind, rng.choice(NODE_NAMES)))
        elif kind == "create_pdb":
            style = rng.randrange(4)
            ops.append((kind, rng.choice(PDB_NAMES), ns,
                        f"app-{rng.randrange(3)}", style))
        elif kind in ("get", "read", "evict"):
            ops.append((kind, pod, ns))
        elif kind == "list":
            ops.append((kind, rng.choice(KINDS),
                        rng.choice([None, ns]),
                        rng.random() < 0.3, rng.randrange(3)))
        elif kind == "scan":
            ops.append((kind, rng.choice(KINDS)))
        elif kind == "pods_on_node":
            ops.append((kind, rng.choice(NODE_NAMES)))
        elif kind in ("update", "patch"):
            ops.append((kind, pod, ns, i, rng.random() < 0.25))  # 25% stale
        elif kind == "delete":
            ops.append((kind, pod, ns))
        elif kind == "delete_precond":
            ops.append((kind, pod, ns, rng.random() < 0.5))  # 50% mismatch
        elif kind == "bind":
            ops.append((kind, pod, ns, rng.choice(NODE_NAMES)))
        elif kind == "bulk_bind":
            ops.append((kind, tuple(rng.sample(POD_NAMES, 3)), ns,
                        rng.choice(NODE_NAMES)))
    return ops


def _apply(store, op):
    """Execute one descriptor; returns ("ok", normalized) or the raised
    ApiError subclass name — the differential unit of comparison."""
    kind = op[0]
    try:
        if kind == "create_pod":
            _, name, ns, app, fin = op
            return ("ok", _norm_result(store.create(_pod(
                name, ns, labels={"app": app},
                finalizers=["test/finalizer"] if fin else []))))
        if kind == "create_node":
            return ("ok", _norm_result(store.create(_node(op[1]))))
        if kind == "create_pdb":
            _, name, ns, app, style = op
            kwargs = [{}, {"min_available": 1}, {"max_unavailable": "50%"},
                      {"min_available": 1, "max_unavailable": 1}][style]
            return ("ok", _norm_result(store.create(_pdb(name, ns, app,
                                                         **kwargs))))
        if kind == "get":
            return ("ok", _norm_result(store.get("Pod", op[1], op[2])))
        if kind == "read":
            return ("ok", store.read("Pod", op[1], op[2],
                                     lambda p: (p.metadata.name,
                                                p.spec.node_name or "")))
        if kind == "list":
            _, k, ns, use_sel, app_i = op
            sel = LabelSelector(match_labels={"app": f"app-{app_i}"}) \
                if use_sel else None
            return ("ok", _norm_result(store.list(k, namespace=ns,
                                                  label_selector=sel)))
        if kind == "scan":
            return ("ok", sorted(store.scan(
                op[1], lambda o: (o.metadata.namespace, o.metadata.name))))
        if kind == "pods_on_node":
            return ("ok", _norm_result(store.pods_on_node(op[1])))
        if kind == "update":
            _, name, ns, i, stale = op
            obj = store.get("Pod", name, ns)
            obj.metadata.labels["updated"] = str(i)
            if stale:
                obj.metadata.resource_version -= 1
            return ("ok", _norm_result(store.update(obj)))
        if kind == "patch":
            _, name, ns, i, drop_finalizer = op

            def fn(o):
                o.metadata.labels["patched"] = str(i)
                if drop_finalizer:
                    o.metadata.finalizers = []
            return ("ok", _norm_result(store.patch("Pod", name, ns, fn)))
        if kind == "delete":
            return ("ok", _norm_result(store.delete("Pod", op[1], op[2])))
        if kind == "delete_precond":
            _, name, ns, mismatch = op
            rv = "999999" if mismatch else str(store.read(
                "Pod", name, ns, lambda p: p.metadata.resource_version))
            return ("ok", _norm_result(store.delete(
                "Pod", name, ns, precondition_rv=rv)))
        if kind == "bind":
            _, name, ns, node = op
            return ("ok", store.bind_pod(_pod(name, ns), node))
        if kind == "bulk_bind":
            _, names, ns, node = op
            return ("ok", store.bind_pods([_pod(n, ns) for n in names], node))
        if kind == "evict":
            return ("ok", store.evict_pod(op[1], op[2]))
        raise AssertionError(f"unknown op {kind}")
    except ApiError as e:
        return ("err", type(e).__name__)


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_differential_serialized_trace(seed):
    """Striped store == naive store for every op of a seeded trace: same
    outcome per op, identical full state after each op (RVs, UIDs,
    timestamps included — both layouts draw from identical sequences)."""
    clock.DEFAULT.set(1_000_000.0)
    rng = random.Random(seed)
    striped, naive = KubeCore(), NaiveKubeCore()
    for step, op in enumerate(_trace(rng, 400)):
        got = _apply(striped, op)
        want = _apply(naive, op)
        assert got == want, f"seed={seed} step={step} op={op}"
        assert _dump(striped) == _dump(naive), \
            f"seed={seed} step={step}: state diverged after {op}"
    # the trace must have exercised both outcome classes to mean anything
    assert any(k != "" for k in striped._stripes), "no stripes created"
    assert len(striped._stripes) > 1, "striping never engaged"
    assert len(naive._stripes) == 1, "naive layout grew stripes"


def _strip_rv(state):
    for objs in state.values():
        for o in objs:
            o.metadata.resource_version = 0
            o.metadata.uid = ""
    return state


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_differential_concurrent_bind_evict(seed):
    """Racing binders/evictors/creators on the striped store converge to
    the same final state the naive store reaches serially: the op set is
    disjoint per thread, so the outcome is order-independent and only the
    RV/UID *ordering* may differ."""
    clock.DEFAULT.set(1_000_000.0)
    rng = random.Random(seed)
    striped, naive = KubeCore(), NaiveKubeCore()
    base = [(f"race-{i}", "default") for i in range(60)]
    for name, ns in base:
        for store in (striped, naive):
            store.create(_pod(name, ns, labels={"app": "race"}))
    bind_a = [n for n, _ in base[:20]]
    bind_b = [n for n, _ in base[20:40]]
    evict = [n for n, _ in base[40:60]]
    extra = [f"late-{i}" for i in range(20)]
    errors = []

    def _run(fn):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — surfaced by the assert below
            errors.append(e)

    threads = [
        threading.Thread(target=_run, args=(lambda: [
            striped.bind_pods([_pod(n, "default") for n in bind_a[i:i + 4]],
                              "node-a") for i in range(0, 20, 4)],)),
        threading.Thread(target=_run, args=(lambda: [
            striped.bind_pod(_pod(n, "default"), "node-b")
            for n in bind_b],)),
        threading.Thread(target=_run, args=(lambda: [
            striped.evict_pod(n, "default") for n in evict],)),
        threading.Thread(target=_run, args=(lambda: [
            striped.create(_node(n)) for n in extra],)),
    ]
    rng.shuffle(threads)
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), f"seed={seed}: thread deadlocked"
    assert not errors, f"seed={seed}: {errors}"

    # the same ops serially on the naive reference
    for i in range(0, 20, 4):
        naive.bind_pods([_pod(n, "default") for n in bind_a[i:i + 4]],
                        "node-a")
    for n in bind_b:
        naive.bind_pod(_pod(n, "default"), "node-b")
    for n in evict:
        naive.evict_pod(n, "default")
    for n in extra:
        naive.create(_node(n))
    assert _strip_rv(_dump(striped)) == _strip_rv(_dump(naive)), \
        f"seed={seed}: concurrent striped result != serial naive result"
    # and the node index agrees with the observable state
    assert sorted(p.metadata.name for p in striped.pods_on_node("node-a")) \
        == sorted(bind_a)
    assert sorted(p.metadata.name for p in striped.pods_on_node("node-b")) \
        == sorted(bind_b)


@pytest.mark.parametrize("seed", [1, 7, 42])
def test_concurrent_evictions_respect_pdb_atomically(seed):
    """The cross-stripe check-then-delete is atomic: with 10 healthy pods
    and minAvailable=8, AT MOST 2 of 6 concurrent evictions may succeed —
    any interleaving that let a third through would mean the PDB check and
    the delete were not one step."""
    core = KubeCore()
    core.create(_pdb("guard", "default", "guarded", min_available=8))
    names = [f"guarded-{i}" for i in range(10)]
    for n in names:
        core.create(_pod(n, "default", labels={"app": "guarded"}))
        core.bind_pod(_pod(n, "default"), "node-x")
    rng = random.Random(seed)
    targets = rng.sample(names, 6)
    outcomes = []
    lock = threading.Lock()

    def _evict(name):
        try:
            core.evict_pod(name, "default")
            ok = True
        except ApiError:
            ok = False
        with lock:
            outcomes.append(ok)

    threads = [threading.Thread(target=_evict, args=(n,)) for n in targets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), "eviction deadlocked"
    assert sum(outcomes) == 2, \
        f"seed={seed}: {sum(outcomes)} evictions passed a minAvailable=8 " \
        f"budget over 10 pods (exactly 2 may)"
    healthy = core.scan("Pod", lambda p: bool(p.spec.node_name))
    assert sum(healthy) == 8


def test_cross_stripe_chaos_never_deadlocks():
    """Lock-order soak: every cross-stripe op class at once — evictions
    (Pod+PDB stripes), watch(None) world snapshots (guard + all stripes),
    brand-new-kind creates (guard), scans — all threads must finish."""
    core = KubeCore()
    core.create(_pdb("pdb", "default", "app", min_available=0))
    for i in range(30):
        core.create(_pod(f"p-{i}", "default", labels={"app": "app"}))
    stop = threading.Event()
    errors = []

    def _loop(fn):
        try:
            while not stop.is_set():
                fn()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    i = [0]

    def _new_kind():
        i[0] += 1
        core.create(Node(metadata=ObjectMeta(name=f"n-{i[0]}"),
                         ))

    def _world_watch():
        q = core.watch(None)
        core.unwatch(q)

    def _evict():
        try:
            core.evict_pod(f"p-{i[0] % 30}", "default")
        except ApiError:
            pass

    threads = [threading.Thread(target=_loop, args=(fn,)) for fn in
               (_new_kind, _world_watch, _evict,
                lambda: core.scan("Pod", lambda p: p.metadata.name),
                lambda: core.list("PodDisruptionBudget"))]
    for t in threads:
        t.start()
    stop_timer = threading.Timer(2.0, stop.set)
    stop_timer.start()
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), "cross-stripe op deadlocked"
    stop_timer.cancel()
    assert not errors, errors


# ---------------------------------------------------------------------------
# Watch semantics under striping
# ---------------------------------------------------------------------------

class TestWatchUnderStriping:
    def test_watchers_list_is_copy_on_write(self):
        """watch/unwatch REPLACE _watchers; the old list object is never
        mutated — the invariant that lets _notify iterate lock-free."""
        core = KubeCore()
        q1 = core.watch("Pod")
        snapshot = core._watchers
        content = list(snapshot)
        q2 = core.watch("Node")
        assert core._watchers is not snapshot
        assert snapshot == content, "registered watcher mutated the old list"
        core.unwatch(q1)
        assert core._watchers is not snapshot
        assert snapshot == content, "unwatch mutated the old list"
        core.unwatch(q2)

    def test_mid_write_watcher_sees_pre_or_post_never_torn(self):
        """A writer flips a pod between two internally consistent label
        states; watchers registered mid-flight must replay one of the two
        states, never a mix (registration + replay run under the same
        stripe lock as the write)."""
        core = KubeCore()
        core.create(_pod("flip", "default", labels={"v": "a", "check": "a"}))
        stop = threading.Event()

        def _writer():
            v = "b"
            while not stop.is_set():
                def fn(o, v=v):
                    o.metadata.labels["v"] = v
                    o.metadata.labels["check"] = v
                core.patch("Pod", "flip", "default", fn)
                v = "a" if v == "b" else "b"

        t = threading.Thread(target=_writer)
        t.start()
        try:
            for _ in range(200):
                q = core.watch("Pod")
                seen = 0
                while True:
                    try:
                        ev = q.get_nowait()
                    except Exception:
                        break
                    labels = ev.obj.metadata.labels
                    assert labels["v"] == labels["check"], \
                        f"torn object observed: {labels}"
                    seen += 1
                    if seen >= 5:
                        break
                core.unwatch(q)
        finally:
            stop.set()
            t.join(timeout=10.0)
        assert not t.is_alive()

    def test_registration_never_loses_an_object(self):
        """Every object lands in the replay XOR as a later ADDED — a
        watcher registered mid-create-storm misses nothing and sees no
        duplicates."""
        core = KubeCore()
        total = 300
        started = threading.Event()

        def _creator():
            started.set()
            for i in range(total):
                core.create(_pod(f"storm-{i}", "default"))

        t = threading.Thread(target=_creator)
        t.start()
        started.wait()
        q = core.watch("Pod", meta_only=True)
        t.join(timeout=30.0)
        assert not t.is_alive()
        seen = []
        while True:
            try:
                seen.append(q.get_nowait().obj.metadata.name)
            except Exception:
                break
        assert len(seen) == len(set(seen)), "duplicate watch delivery"
        assert set(seen) == {f"storm-{i}" for i in range(total)}, \
            f"lost {total - len(seen)} objects across registration"
        core.unwatch(q)

    def test_world_watch_replays_every_kind_and_meta_only_stubs(self):
        core = KubeCore()
        core.create(_pod("p", "default"))
        core.create(_node("n"))
        q = core.watch(None, meta_only=True)
        replay = [q.get_nowait() for _ in range(2)]
        assert {e.obj.kind for e in replay} == {"Pod", "Node"}
        assert all(isinstance(e.obj, MetaObj) for e in replay)
        # post-registration events for a brand-new kind still arrive
        core.create(_pdb("pdb", "default", "x"))
        ev = q.get(timeout=2.0)
        assert ev.type == "ADDED" and ev.obj.kind == "PodDisruptionBudget"
        core.unwatch(q)

    def test_full_copy_watch_events_are_isolated_copies(self):
        """Non-meta watches deliver deep copies: mutating a delivered
        event object must not reach the store (deep_copy fidelity via
        the COW notify path)."""
        core = KubeCore()
        q = core.watch("Pod")
        core.create(_pod("iso", "default", labels={"k": "v"}))
        ev = q.get(timeout=2.0)
        ev.obj.metadata.labels["k"] = "mutated"
        assert core.read("Pod", "iso", "default",
                         lambda p: p.metadata.labels["k"]) == "v"
        assert deep_copy(ev.obj).metadata.labels["k"] == "mutated"
        core.unwatch(q)
