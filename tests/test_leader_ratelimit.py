"""Leader election (Lease protocol) and the token-bucket rate limiter."""

import threading

from karpenter_tpu.api.core import Lease
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.runtime.leaderelection import LEASE_NAME, LeaderElector
from karpenter_tpu.utils import clock
from karpenter_tpu.utils.ratelimit import TokenBucket


class FakeTime:
    def __init__(self):
        self.t = 0.0
        self.slept = []

    def now(self):
        return self.t

    def sleep(self, s):
        self.slept.append(s)
        self.t += s


class TestTokenBucket:
    def test_burst_then_qps(self):
        ft = FakeTime()
        b = TokenBucket(qps=2, burst=3, timefunc=ft.now, sleepfunc=ft.sleep)
        for _ in range(3):
            assert b.acquire() == 0.0  # burst is free
        waited = b.acquire()           # 4th must wait 1/qps
        assert abs(waited - 0.5) < 1e-9

    def test_refill_caps_at_burst(self):
        ft = FakeTime()
        b = TokenBucket(qps=10, burst=2, timefunc=ft.now, sleepfunc=ft.sleep)
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()
        ft.t += 100.0  # long idle: refill caps at burst, not qps*dt
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()


class TestLeaderElection:
    def setup_method(self):
        clock.DEFAULT.set(3_000_000.0)

    def teardown_method(self):
        clock.DEFAULT.reset()

    def test_first_candidate_wins_second_waits(self):
        kube = KubeCore()
        a = LeaderElector(kube, identity="a")
        b = LeaderElector(kube, identity="b")
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False
        # the holder renews; the candidate still loses
        clock.DEFAULT.advance(5)
        assert a.try_acquire_or_renew() is True
        assert b.try_acquire_or_renew() is False

    def test_expired_lease_is_taken_over(self):
        kube = KubeCore()
        a = LeaderElector(kube, identity="a", lease_duration=15)
        b = LeaderElector(kube, identity="b", lease_duration=15)
        assert a.try_acquire_or_renew()
        clock.DEFAULT.advance(16)  # a stopped renewing
        assert b.try_acquire_or_renew() is True
        lease = kube.get("Lease", LEASE_NAME)
        assert lease.spec.holder_identity == "b"
        # a cannot renew anymore
        assert a.try_acquire_or_renew() is False

    def test_release_on_stop_frees_lease(self):
        kube = KubeCore()
        a = LeaderElector(kube, identity="a")
        assert a.try_acquire_or_renew()
        a._leading = True
        a.stop()
        lease = kube.get("Lease", LEASE_NAME)
        assert lease.spec.holder_identity == ""
        b = LeaderElector(kube, identity="b")
        assert b.try_acquire_or_renew() is True  # no wait-out needed

    def test_run_loop_transitions(self):
        kube = KubeCore()
        started = threading.Event()
        a = LeaderElector(kube, identity="a", renew_period=0.02,
                          on_started_leading=started.set)
        a.start()
        assert started.wait(timeout=5.0)
        assert a.is_leader()
        a.stop()

    def test_over_the_wire(self):
        """The same protocol through KubeApiClient + the stub server."""
        import time as _t

        from tests.test_kubeclient import StubHandler
        from http.server import ThreadingHTTPServer
        from karpenter_tpu.runtime.kubeclient import KubeApiClient

        core = KubeCore()
        handler = type("S", (StubHandler,), {"core": core})
        server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        client = KubeApiClient(f"http://127.0.0.1:{server.server_address[1]}")
        try:
            a = LeaderElector(client, identity="a")
            b = LeaderElector(client, identity="b")
            assert a.try_acquire_or_renew() is True
            assert b.try_acquire_or_renew() is False
            stored = core.get("Lease", LEASE_NAME)
            assert stored.spec.holder_identity == "a"
            assert isinstance(client.get("Lease", LEASE_NAME), Lease)
            clock.DEFAULT.advance(20)
            assert b.try_acquire_or_renew() is True
        finally:
            server.shutdown()


class TestElectionRobustness:
    def setup_method(self):
        clock.DEFAULT.set(4_000_000.0)

    def teardown_method(self):
        clock.DEFAULT.reset()

    def test_api_error_demotes_instead_of_killing_thread(self):
        kube = KubeCore()
        stopped = threading.Event()
        a = LeaderElector(kube, identity="a", renew_period=0.02,
                          on_stopped_leading=stopped.set)
        started = threading.Event()
        a.on_started_leading = started.set
        a.start()
        assert started.wait(5.0)
        # sabotage the API: every round now raises
        def boom(*args, **kw):
            raise OSError("api down")
        a.kube = type("K", (), {"get": boom, "create": boom, "update": boom,
                                "patch": boom})()
        assert stopped.wait(5.0), "leader must demote on API failure"
        assert not a.is_leader()
        assert a._thread.is_alive()  # the loop survives to campaign again
        a.kube = kube  # API back: must re-acquire (lease still ours/expired)
        clock.DEFAULT.advance(60)
        started2 = threading.Event()
        a.on_started_leading = started2.set
        assert started2.wait(5.0)
        a.stop()

    def test_stop_does_not_strand_lease_on_dead_identity(self):
        kube = KubeCore()
        a = LeaderElector(kube, identity="a", renew_period=0.01)
        started = threading.Event()
        a.on_started_leading = started.set
        a.start()
        assert started.wait(5.0)
        a.stop()
        lease = kube.get("Lease", LEASE_NAME)
        assert lease.spec.holder_identity != "a" or lease.spec.renew_time is None
        b = LeaderElector(kube, identity="b")
        assert b.try_acquire_or_renew() is True  # immediate, no wait-out

    def test_wait_for_leadership_timeout_is_wall_time(self):
        kube = KubeCore()
        blocker = LeaderElector(kube, identity="holder")
        assert blocker.try_acquire_or_renew()
        loser = LeaderElector(kube, identity="loser", renew_period=0.02)
        loser.start()
        # frozen injectable clock: the wall-time deadline must still fire
        assert loser.wait_for_leadership(timeout=0.3) is False
        loser.stop()
