"""Process-level smoke of the controller entrypoint.

Everything else tests components in-process; this launches
``python -m karpenter_tpu.main`` as the deployment artifact actually runs
(cmd/controller/main.go analog): CLI parsing, all controllers registered,
/metrics + /healthz + /readyz served, clean SIGTERM shutdown.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(port, path, timeout=2.0):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:  # 4xx/5xx carry a status too
        return e.code, e.read().decode()


class TestMainProcess:
    def test_entrypoint_serves_and_shuts_down_cleanly(self):
        port = _free_port()
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO}
        proc = subprocess.Popen(
            [sys.executable, "-m", "karpenter_tpu.main",
             "--cluster-name", "smoke",
             "--cluster-endpoint", "http://localhost:6443",
             "--cloud-provider", "fake",
             "--kube-backend", "memory",
             "--metrics-port", str(port)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        # drain continuously: a chatty controller filling the 64KB pipe
        # buffer would block in write() and deadlock the shutdown
        captured: list = []
        drainer = threading.Thread(
            target=lambda: captured.extend(proc.stdout), daemon=True)
        drainer.start()
        try:
            deadline = time.monotonic() + 30.0
            last_err = None
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    drainer.join(timeout=5.0)
                    out = "".join(captured)
                    pytest.fail(f"controller exited early rc={proc.returncode}:"
                                f"\n{out[-2000:]}")
                try:
                    status, body = _get(port, "/healthz")
                    # body carries the pressure rung: "ok level=L0"
                    if status == 200 and body.startswith("ok"):
                        break
                except OSError as e:
                    last_err = e
                    time.sleep(0.2)
            else:
                pytest.fail(f"/healthz never answered: {last_err}")

            status, _ = _get(port, "/readyz")
            assert status == 200
            status, metrics = _get(port, "/metrics")
            assert status == 200
            # the registry serves the solver health series from process start
            assert "karpenter_solver_breaker_open" in metrics
            status, _ = _get(port, "/nope")
            assert status == 404

            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=30.0)
            assert rc == 0, f"SIGTERM exit rc={rc}"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10.0)

    def test_invalid_options_exit_nonzero(self):
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
        proc = subprocess.run(
            [sys.executable, "-m", "karpenter_tpu.main",
             "--cloud-provider", "fake", "--kube-backend", "memory"],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1  # cluster-name/endpoint are required
