"""Marshal-cache correctness: the cached pod-vector/packables fast path must
be bit-identical to the uncached computation, and staleness must be
structurally impossible (new objects → new identity tokens).

The cache exists because the 200 ms p99 budget INCLUDES marshal of 50k pods
(SURVEY.md §7); see solver/adapter.py module docstring.
"""

import copy

from karpenter_tpu.api.core import Container, Pod, PodSpec, ResourceRequirements
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.solver.adapter import (
    _compute_pod_marshal, _required_resources, build_packables,
    build_packables_cached, invalidate_pod_marshal, pod_special_mask,
    pod_vector, pod_vectors,
)
from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.solver.host_ffd import R_CPU, R_MEMORY, R_NVIDIA


def make_catalog_simple():
    return instance_types(6)


def make_pod(requests=None, limits=None):
    return Pod(spec=PodSpec(containers=[Container(
        resources=ResourceRequirements.make(requests=requests, limits=limits))]))


class TestPodVectorCache:
    def test_cached_equals_computed(self):
        pod = make_pod({"cpu": "250m", "memory": "1Gi"})
        vec = pod_vector(pod)
        assert vec == _compute_pod_marshal(pod)[0]
        assert vec[R_CPU] == 250 * 10**6
        assert vec[R_MEMORY] == 2**30 * 10**9
        # second call returns the identical cached tuple
        assert pod_vector(pod) is vec

    def test_special_mask_requests_and_limits(self):
        # requiresResource (packable.go:221-233) checks requests OR limits
        by_request = make_pod({"nvidia.com/gpu": "1"})
        by_limit = make_pod({"cpu": "1"}, limits={"nvidia.com/gpu": "1"})
        neither = make_pod({"cpu": "1"})
        assert pod_special_mask(by_request) == pod_special_mask(by_limit) != 0
        assert pod_special_mask(neither) == 0
        assert pod_vector(by_request)[R_NVIDIA] == 10**9
        # a limits-only GPU request reserves nothing but still gates viability
        assert pod_vector(by_limit)[R_NVIDIA] == 0

    def test_required_resources_from_masks(self):
        pods = [make_pod({"cpu": "1"}) for _ in range(10)]
        pods.append(make_pod({"cpu": "1"}, limits={"amd.com/gpu": "2"}))
        assert _required_resources(pods) == frozenset({"amd.com/gpu"})

    def test_invalidate(self):
        pod = make_pod({"cpu": "1"})
        v0 = pod_vector(pod)
        pod.spec.containers[0].resources = ResourceRequirements.make(
            requests={"cpu": "2"})
        assert pod_vector(pod) is v0  # stale until invalidated
        invalidate_pod_marshal(pod)
        assert pod_vector(pod)[R_CPU] == 2 * 10**9

    def test_deepcopy_carries_cache(self):
        pod = make_pod({"cpu": "3"})
        v0 = pod_vector(pod)
        clone = copy.deepcopy(pod)
        assert pod_vector(clone) == v0

    def test_bulk_gather_matches(self):
        pods = [make_pod({"cpu": f"{i % 7 + 1}", "memory": f"{i % 5 + 1}Gi"})
                for i in range(200)]
        assert pod_vectors(pods) == [_compute_pod_marshal(p)[0] for p in pods]

    def test_codec_primes_cache(self):
        from karpenter_tpu.api.codec_core import pod_from

        pod = pod_from({"metadata": {"name": "x"}, "spec": {"containers": [
            {"name": "app", "resources": {"requests": {"cpu": "500m"}}}]}})
        assert "_marshal" in pod.__dict__
        assert pod_vector(pod)[R_CPU] == 500 * 10**6


class TestPackablesCache:
    def test_hit_is_bit_identical_and_mutation_safe(self):
        catalog = make_catalog_simple()
        constraints = universe_constraints(catalog)
        pods = [make_pod({"cpu": "1"})]
        want_p, want_t = build_packables(catalog, constraints, pods, [])
        got1_p, got1_t = build_packables_cached(catalog, constraints, pods, [])
        got2_p, got2_t = build_packables_cached(catalog, constraints, pods, [])
        key = lambda ps: [(p.index, p.total, p.reserved) for p in ps]
        assert key(got1_p) == key(got2_p) == key(want_p)
        assert got1_t == got2_t == want_t
        # hits hand out fresh copies: mutating one must not poison the cache
        got1_p[0].reserved[0] += 999
        got3_p, _ = build_packables_cached(catalog, constraints, pods, [])
        assert key(got3_p) == key(want_p)

    def test_new_catalog_objects_never_hit_stale(self):
        # a provider refresh builds NEW InstanceType objects → new tokens →
        # recompute, even if the old catalog had identical values
        catalog1 = make_catalog_simple()
        catalog2 = make_catalog_simple()
        constraints = universe_constraints(catalog1)
        pods = [make_pod({"cpu": "1"})]
        build_packables_cached(catalog1, constraints, pods, [])
        catalog2[0].cpu = copy.copy(catalog2[0].cpu)
        catalog2[0].cpu.nano *= 2  # semantically different catalog
        got_p, _ = build_packables_cached(catalog2, constraints, pods, [])
        want_p, _ = build_packables(catalog2, constraints, pods, [])
        assert [(p.index, p.total) for p in got_p] == \
            [(p.index, p.total) for p in want_p]

    def test_required_resources_partition_cache_key(self):
        catalog = make_catalog_simple()
        constraints = universe_constraints(catalog)
        plain = [make_pod({"cpu": "1"})]
        gpu = [make_pod({"cpu": "1"}, limits={"nvidia.com/gpu": "1"})]
        p_plain, _ = build_packables_cached(catalog, constraints, plain, [])
        p_gpu, _ = build_packables_cached(catalog, constraints, gpu, [])
        w_plain, _ = build_packables(catalog, constraints, plain, [])
        w_gpu, _ = build_packables(catalog, constraints, gpu, [])
        assert len(p_plain) == len(w_plain)
        assert len(p_gpu) == len(w_gpu)

    def test_daemons_enter_key(self):
        catalog = make_catalog_simple()
        constraints = universe_constraints(catalog)
        pods = [make_pod({"cpu": "1"})]
        daemon = make_pod({"cpu": "500m"})
        p0, _ = build_packables_cached(catalog, constraints, pods, [])
        p1, _ = build_packables_cached(catalog, constraints, pods, [daemon])
        w1, _ = build_packables(catalog, constraints, pods, [daemon])
        assert [(p.reserved) for p in p1] == [(p.reserved) for p in w1]
        assert [(p.reserved) for p in p0] != [(p.reserved) for p in p1]


class TestMarshalPods:
    def test_one_pass_matches_two(self):
        from karpenter_tpu.solver.adapter import marshal_pods

        pods = [make_pod({"cpu": "1"}) for _ in range(20)]
        pods.append(make_pod({"cpu": "1"}, limits={"nvidia.com/gpu": "1"}))
        vecs, required = marshal_pods(pods)
        assert vecs == pod_vectors(pods)
        assert required == _required_resources(pods)
        assert required == frozenset({"nvidia.com/gpu"})
