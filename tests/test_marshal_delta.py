"""Round-10 differential suite: the delta-marshal arena, the versioned
catalog encoding, and the token-aware device ring (docs/solver.md §14).

The contract under test is the encode.py exactness rule: every cache is
versioned, a version mismatch means a rebuild, and NO input — churn,
provisioner spec change, intern-table rollover, or a concurrent reset
landing mid-window — may ever produce bytes that differ from a cold
from-scratch marshal+encode.
"""

import random
import threading

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.ops import encode as enc_mod
from karpenter_tpu.ops import feasibility
from karpenter_tpu.solver import adapter
from karpenter_tpu.solver.solve import SolverConfig, solve
from tests.test_pack_parity import make_pod

SHAPES = [(100, 64), (250, 128), (500, 256), (1000, 512), (2000, 1024),
          (4000, 4096)]


def mixed_pods(rng, n):
    pods = []
    for i in range(n):
        c, m = SHAPES[rng.randrange(len(SHAPES))]
        pods.append(make_pod({"cpu": f"{c}m", "memory": f"{m}Mi"}))
    return pods


def cold_clear(pods):
    """The pre-round-10 state: no arena, no per-pod handles, no cached
    catalog tensors."""
    for p in pods:
        p.__dict__.pop("_marshal", None)
        p.__dict__.pop("_arena_row", None)
    enc_mod.reset_marshal_arena()
    enc_mod.clear_catalog_encoding_cache()


def marshal_key(pods):
    """Everything marshal_pods_interned feeds the encoder, materialized."""
    vecs, required, sids = adapter.marshal_pods_interned(pods)
    return (list(vecs), required,
            None if sids is None else sids[0].tolist())


class TestDeltaEqualsCold:
    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_churned_windows_bit_for_bit(self, seed):
        """Five windows with ~20% object churn: the warm (delta) marshal
        must equal a cold full marshal exactly — vectors, required set,
        and interned shape ids."""
        rng = random.Random(seed)
        pods = mixed_pods(rng, 300)
        cold_clear(pods)
        for _ in range(5):
            for idx in rng.sample(range(len(pods)), len(pods) // 5):
                c, m = SHAPES[rng.randrange(len(SHAPES))]
                pods[idx] = make_pod({"cpu": f"{c}m", "memory": f"{m}Mi"})
            delta = marshal_key(pods)
            cold_clear(pods)
            cold = marshal_key(pods)
            assert delta[0] == cold[0]
            assert delta[1] == cold[1]
            assert delta[2] == cold[2]

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_encode_bit_for_bit_through_versioned_catalog(self, seed):
        """The full window encode (marshal + versioned catalog tensors)
        delta vs cold, compared on raw array bytes."""
        rng = random.Random(seed)
        catalog = instance_types(8)
        constraints = universe_constraints(catalog)
        pods = mixed_pods(rng, 200)
        cold_clear(pods)

        def window_encode():
            vecs, required, sids = adapter.marshal_pods_interned(pods)
            packables, _st, ver = adapter.build_packables_versioned(
                catalog, constraints, pods, [], required=required)
            e = enc_mod.encode(vecs, list(range(len(pods))), packables,
                               pad=False, sids=sids, catalog_version=ver)
            return (e.shapes.tobytes(), e.counts.tobytes(),
                    e.totals.tobytes(), e.reserved0.tobytes(),
                    e.valid.tobytes(), e.shape_pods, e.scales, e.pods_unit)

        window_encode()  # warm
        for _ in range(3):
            for idx in rng.sample(range(len(pods)), len(pods) // 10):
                c, m = SHAPES[rng.randrange(len(SHAPES))]
                pods[idx] = make_pod({"cpu": f"{c}m", "memory": f"{m}Mi"})
            warm = window_encode()
            cold_clear(pods)
            assert window_encode() == warm


class TestInvalidation:
    def test_spec_change_mints_new_catalog_version(self):
        """A provisioner spec change (different allowed sets) must land on
        a new packables version — the encoder can never serve the old
        spec's catalog tensors to the new spec."""
        from karpenter_tpu.api import wellknown
        from karpenter_tpu.api.core import NodeSelectorRequirement as Req

        catalog = instance_types(6)
        constraints = universe_constraints(catalog)
        pods = mixed_pods(random.Random(3), 40)
        _p1, _s1, v1 = adapter.build_packables_versioned(
            catalog, constraints, pods, [])
        tightened = constraints.deepcopy()
        tightened.requirements = tightened.requirements.add(
            Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
                values=["test-zone-1"]))
        _p2, _s2, v2 = adapter.build_packables_versioned(
            catalog, tightened, pods, [])
        assert v1 != v2
        # and the same spec repeats its version (cache hit, same bytes)
        _p3, _s3, v3 = adapter.build_packables_versioned(
            catalog, constraints, pods, [])
        assert v3 == v1

    def test_spec_change_solve_parity(self):
        """Back-to-back solves under two different specs, arena warm
        throughout: each result equals its own cold solve."""
        from karpenter_tpu.api import wellknown
        from karpenter_tpu.api.core import NodeSelectorRequirement as Req

        catalog = instance_types(8)
        base = universe_constraints(catalog)
        tightened = base.deepcopy()
        tightened.requirements = tightened.requirements.add(
            Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
                values=["test-zone-2"]))
        pods = mixed_pods(random.Random(11), 120)
        cold_clear(pods)
        warm = [solve(base, pods, catalog).node_count,
                solve(tightened, pods, catalog).node_count,
                solve(base, pods, catalog).node_count]
        cold_counts = []
        for c in (base, tightened, base):
            cold_clear(pods)
            cold_counts.append(solve(c, pods, catalog).node_count)
        assert warm == cold_counts

    def test_intern_table_rollover_rebinds_arena(self, monkeypatch):
        """Force the adapter intern table over its cap mid-stream: the
        arena must follow the generation rebind (never serving rows keyed
        by dead shape ids) and the marshal stays exact."""
        monkeypatch.setattr(adapter, "_INTERN_MAX", 4)
        monkeypatch.setattr(adapter, "_INTERN_GEN", 50_000)
        monkeypatch.setattr(adapter, "_VEC_INTERN", {})
        monkeypatch.setattr(adapter, "_VEC_BY_ID", [])
        enc_mod.reset_marshal_arena()
        rng = random.Random(7)
        # > _INTERN_MAX distinct shapes: guaranteed rollovers
        pods = [make_pod({"cpu": f"{100 + 10 * i}m", "memory": "64Mi"})
                for i in range(12)]
        for _ in range(3):
            delta = marshal_key(pods)
            oracle = [adapter.pod_vector(p) for p in pods]
            assert delta[0] == oracle
            for idx in rng.sample(range(len(pods)), 3):
                pods[idx] = make_pod(
                    {"cpu": f"{100 + 10 * rng.randrange(40)}m",
                     "memory": "64Mi"})

    def test_feasibility_vocab_rebind_resets_arena(self):
        """A feasibility intern-table generation rebind (the provisioner
        spec-change signal) must bump the arena generation on the next
        window — and the marshal stays exact across it."""
        pods = mixed_pods(random.Random(13), 50)
        cold_clear(pods)
        marshal_key(pods)
        gen0 = enc_mod.marshal_arena().stats()["generation"]
        feasibility.reset_intern_table()
        delta = marshal_key(pods)
        assert enc_mod.marshal_arena().stats()["generation"] > gen0
        assert delta[0] == [adapter.pod_vector(p) for p in pods]


class TestChaos:
    def test_mid_window_reset_never_stale(self, monkeypatch):
        """A concurrent arena reset landing between assign() and gather()
        must void the attempt (restart or scan fallback), never splice old
        rows into the window tensor."""
        pods = mixed_pods(random.Random(5), 60)
        cold_clear(pods)
        marshal_key(pods)  # warm rows
        real_gather = enc_mod.MarshalArena.gather
        hits = {"n": 0}

        def chaotic_gather(self, rows, generation):
            if hits["n"] < 2:
                hits["n"] += 1
                # the concurrent-reset race: the process arena is replaced
                # between this window's assigns and its gather
                enc_mod.reset_marshal_arena()
                return None
            return real_gather(self, rows, generation)

        monkeypatch.setattr(enc_mod.MarshalArena, "gather", chaotic_gather)
        delta = adapter.marshal_pods_interned(pods)
        monkeypatch.setattr(enc_mod.MarshalArena, "gather", real_gather)
        oracle = [adapter.pod_vector(p) for p in pods]
        assert list(delta[0]) == oracle
        assert hits["n"] == 2  # the chaos actually fired

    def test_threaded_marshal_with_concurrent_resets(self):
        """Hammer the arena from worker threads while a chaos thread
        resets the arena and both intern tables: every returned window
        must equal the pure per-pod oracle."""
        rng = random.Random(21)
        windows = [mixed_pods(rng, 40) for _ in range(4)]
        oracles = [[adapter.pod_vector(p) for p in w] for w in windows]
        stop = threading.Event()
        errors = []

        def chaos():
            while not stop.is_set():
                enc_mod.reset_marshal_arena()
                feasibility.reset_intern_table()

        def worker(i):
            try:
                for _ in range(30):
                    w = windows[i]
                    vecs, _req, _sids = adapter.marshal_pods_interned(w)
                    if list(vecs) != oracles[i]:
                        errors.append(f"window {i}: stale marshal")
                        return
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(f"window {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(windows))]
        chaos_t = threading.Thread(target=chaos)
        chaos_t.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        chaos_t.join()
        assert not errors, errors


class TestDeviceResidency:
    def test_steady_state_zero_fresh_catalog_transfers(self):
        """The round-10 acceptance property: an identical repeat solve
        through the solo donate ring ships NO fresh catalog bytes — every
        catalog tensor answers by token (reuses), only the donated counts
        buffer refills, and nothing allocates."""
        from karpenter_tpu.solver import pipeline as pl

        pl.reset_ring()
        catalog = instance_types(8)
        constraints = universe_constraints(catalog)
        pods = mixed_pods(random.Random(2), 48)
        cold_clear(pods)
        cfg = SolverConfig(device_min_pods=1, device_donate=True)
        r1 = solve(constraints, pods, catalog, config=cfg)
        c1 = pl.get_ring().counters()
        assert c1["allocations"] > 0
        r2 = solve(constraints, pods, catalog, config=cfg)
        c2 = pl.get_ring().counters()
        assert r1.node_count == r2.node_count
        assert c2["allocations"] == c1["allocations"], (
            f"steady-state solo solve allocated fresh buffers: {c2}")
        # catalog + shape tensors answer by token: totals, reserved0,
        # valid, last_valid, pods_unit, shapes, dropped
        assert c2["reuses"] - c1["reuses"] >= 5
        # the donated counts buffer is NEVER token-reused — it must refill
        assert c2["refills"] > c1["refills"]

    def test_donate_parity_with_no_donate(self):
        catalog = instance_types(8)
        constraints = universe_constraints(catalog)
        pods = mixed_pods(random.Random(9), 64)
        a = solve(constraints, pods, catalog,
                  config=SolverConfig(device_min_pods=1, device_donate=True))
        b = solve(constraints, pods, catalog,
                  config=SolverConfig(device_min_pods=1, device_donate=False))
        assert a.node_count == b.node_count
        key = lambda r: sorted(  # noqa: E731
            (tuple(it.name for it in p.instance_type_options),
             p.node_quantity) for p in r.packings)
        assert key(a) == key(b)

    def test_solo_donated_refill_read_raises(self):
        """Use-after-donate guard on the SOLO ring surface
        (SingleDeviceSharding): after a donating refill of the same slot
        buffer, reading the pre-refill array must raise RuntimeError —
        never return stale bytes."""
        import jax
        from jax.sharding import SingleDeviceSharding

        from karpenter_tpu.solver.pipeline import DeviceRing

        ring = DeviceRing()
        sh = SingleDeviceSharding(jax.devices()[0])
        host = np.arange(8, dtype=np.int32)
        slot = ring.acquire(DeviceRing.signature({"solo_counts": host}))
        first = ring.fill(slot, "solo_counts", host, sh)
        jax.block_until_ready(first)
        second = ring.fill(slot, "solo_counts", host + 1, sh)
        jax.block_until_ready(second)
        assert np.array_equal(np.asarray(second), host + 1)
        assert first.is_deleted()
        with pytest.raises(RuntimeError):
            np.asarray(first)
        ring.release(slot)

    def test_token_reuse_skips_refill_and_hand_back_clears(self):
        """fill(token=) returns the live buffer without any transfer when
        the token matches; hand_back drops the token (kernel output bytes
        are unknown) so the next fill must refill."""
        import jax
        from jax.sharding import SingleDeviceSharding

        from karpenter_tpu.solver.pipeline import DeviceRing

        ring = DeviceRing()
        sh = SingleDeviceSharding(jax.devices()[0])
        host = np.arange(6, dtype=np.int32)
        slot = ring.acquire(DeviceRing.signature({"totals": host}))
        tok = ("cat", 1, (1, 1), 6)
        a = ring.fill(slot, "totals", host, sh, token=tok)
        b = ring.fill(slot, "totals", host, sh, token=tok)
        assert b is a  # no transfer at all
        assert ring.counters()["reuses"] == 1
        # different token: must transfer (refill), then the new token holds
        c = ring.fill(slot, "totals", host + 2, sh, token=("cat", 2, (1, 1), 6))
        jax.block_until_ready(c)
        assert np.array_equal(np.asarray(c), host + 2)
        ring.hand_back(slot, totals=c)
        d = ring.fill(slot, "totals", host + 2, sh,
                      token=("cat", 2, (1, 1), 6))
        jax.block_until_ready(d)
        counters = ring.counters()
        assert counters["reuses"] == 1  # hand_back cleared the token
        ring.release(slot)
