"""Consolidation observability (metrics/consolidation.py).

Every series the batched what-if engine promises must actually be emitted
by a reconcile: the window gauges, the evaluated/filtered/drain counters,
the solve-seconds histogram, and the relaxation used/fallback counters.
The registry is process-wide, so counts are asserted as deltas around one
driven window (the test_metrics_pipeline.py idiom).
"""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import LabelSelector, ObjectMeta, PodDisruptionBudget
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.metrics.consolidation import (
    CONSOLIDATION_CANDIDATES_TOTAL, CONSOLIDATION_DRAINS_TOTAL,
    CONSOLIDATION_FILTERED_TOTAL, CONSOLIDATION_RECLAIMED_TOTAL,
    CONSOLIDATION_RELAX_FALLBACKS, CONSOLIDATION_RELAX_USED,
    CONSOLIDATION_SOLVE_SECONDS, CONSOLIDATION_WINDOW_CANDIDATES,
    CONSOLIDATION_WINDOW_RECLAIMED,
)
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.models.consolidate import repack_plan
from karpenter_tpu.runtime.kubecore import KubeCore

from tests.expectations import make_provisioner
from tests.test_consolidation import priced_catalog, running_node, running_pod
from tests.test_whatif import random_fleet


def _counter(series, **labels):
    key = tuple(sorted(labels.items()))
    return series.collect().get(key, 0.0)


def _histogram_total(series):
    return series.collect().get((), (None, 0.0, 0))[2]


class TestConsolidationSeries:
    @pytest.fixture()
    def env(self):
        kube = KubeCore()
        catalog = priced_catalog()
        provider = FakeCloudProvider(catalog=catalog)
        kube.create(make_provisioner(
            constraints=universe_constraints(catalog),
            consolidation_enabled=True))
        controller = ConsolidationController(kube, provider=provider)
        medium = catalog[1]
        for i in range(3):
            node = running_node(f"node-{i}", medium)
            node.metadata.finalizers.append(wellknown.TERMINATION_FINALIZER)
            kube.create(node)
            for j in range(1 if i == 0 else 3):
                pod = running_pod(f"pod-{i}-{j}", cpu="500m")
                kube.create(pod)
                kube.bind_pod(pod, f"node-{i}")
        return kube, catalog, controller

    def test_window_emits_gauges_counters_and_histogram(self, env):
        kube, catalog, controller = env
        evaluated0 = _counter(CONSOLIDATION_CANDIDATES_TOTAL)
        drains0 = _counter(CONSOLIDATION_DRAINS_TOTAL)
        reclaimed0 = _counter(CONSOLIDATION_RECLAIMED_TOTAL)
        solves0 = _histogram_total(CONSOLIDATION_SOLVE_SECONDS)

        controller.reconcile("default")

        # all three nodes carried movable pods → all entered the batch
        assert CONSOLIDATION_WINDOW_CANDIDATES.collect()[()] == 3.0
        assert _counter(CONSOLIDATION_CANDIDATES_TOTAL) == evaluated0 + 3.0
        assert _histogram_total(CONSOLIDATION_SOLVE_SECONDS) == solves0 + 1
        # node-0 and node-2 drain (node-1 received node-0's pod), each
        # charging the medium price onto the reclaimed counter + gauge
        drains = _counter(CONSOLIDATION_DRAINS_TOTAL) - drains0
        assert drains == 2.0
        reclaimed = _counter(CONSOLIDATION_RECLAIMED_TOTAL) - reclaimed0
        assert reclaimed == pytest.approx(2 * 0.19)
        assert CONSOLIDATION_WINDOW_RECLAIMED.collect()[()] == \
            pytest.approx(reclaimed)

    def test_filtered_counters_by_reason(self, env):
        kube, catalog, controller = env
        dne0 = _counter(CONSOLIDATION_FILTERED_TOTAL, reason="do-not-evict")
        pdb0 = _counter(CONSOLIDATION_FILTERED_TOTAL, reason="pdb")

        pinned = kube.get("Pod", "pod-1-0")
        pinned.metadata.annotations[wellknown.DO_NOT_EVICT_ANNOTATION] = "true"
        kube.update(pinned)
        blocked = kube.get("Pod", "pod-2-0")
        blocked.metadata.labels["app"] = "web"
        kube.update(blocked)
        kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="web-pdb"),
            selector=LabelSelector(match_labels={"app": "web"}),
            min_available=1))

        controller.reconcile("default")

        assert _counter(CONSOLIDATION_FILTERED_TOTAL,
                        reason="do-not-evict") == dne0 + 1.0
        assert _counter(CONSOLIDATION_FILTERED_TOTAL,
                        reason="pdb") == pdb0 + 1.0
        # only node-0 survived the filter into the batch
        assert CONSOLIDATION_WINDOW_CANDIDATES.collect()[()] == 1.0

    def test_relax_counters_cover_used_and_fallback(self):
        used0 = _counter(CONSOLIDATION_RELAX_USED)
        # the crafted case where the relaxation strictly wins (cheaper
        # small-node fleet) must bump the used counter...
        from karpenter_tpu.cloudprovider.fake.provider import make_instance_type

        catalog = [
            make_instance_type("small", cpu="2", memory="4Gi", pods="20",
                               price=0.10),
            make_instance_type("large", cpu="8", memory="16Gi", pods="80",
                               price=0.90),
        ]
        constraints = universe_constraints(catalog)
        nodes = [running_node(f"n{i}", catalog[1]) for i in range(4)]
        pods_by = {
            f"n{i}": [running_pod(f"p{i}-{j}", cpu="1", memory="512Mi")
                      for j in range(2)]
            for i in range(4)}
        plan = repack_plan(nodes, pods_by, constraints, catalog,
                           backend="relax")
        assert plan.relax.used
        assert _counter(CONSOLIDATION_RELAX_USED) == used0 + 1.0

        # ...and a fallback run must bump the reason-labelled counter
        catalog, nodes, pods_by = random_fleet(7, n_nodes=6)
        constraints = universe_constraints(catalog)
        plan = repack_plan(nodes, pods_by, constraints, catalog,
                           backend="relax")
        if not plan.relax.used:
            reason = plan.relax.reason.replace("fallback-", "")
            assert _counter(CONSOLIDATION_RELAX_FALLBACKS,
                            reason=reason) >= 1.0
