"""Registration + exposition of the feasibility filter metrics."""

from __future__ import annotations

from karpenter_tpu.metrics import filter as mfilter
from karpenter_tpu.metrics.registry import DEFAULT, Counter, Gauge, Histogram


class TestFilterMetricsRegistration:
    def test_registered_on_default_registry(self):
        assert isinstance(
            DEFAULT.histogram("filter_batch_seconds"), Histogram)
        assert DEFAULT.histogram("filter_batch_seconds") is \
            mfilter.FILTER_BATCH_SECONDS
        assert isinstance(DEFAULT.counter("filter_fallback_total"), Counter)
        assert DEFAULT.counter("filter_fallback_total") is \
            mfilter.FILTER_FALLBACK_TOTAL
        assert isinstance(DEFAULT.gauge("filter_intern_table_size"), Gauge)
        assert DEFAULT.gauge("filter_intern_table_size") is \
            mfilter.FILTER_INTERN_TABLE_SIZE

    def test_exposition_names_carry_karpenter_prefix(self):
        mfilter.FILTER_BATCH_SECONDS.observe(0.004, stage="schedule")
        mfilter.FILTER_FALLBACK_TOTAL.inc(reason="unsupported-operator")
        mfilter.FILTER_INTERN_TABLE_SIZE.set(17)
        text = DEFAULT.expose()
        assert "# TYPE karpenter_filter_batch_seconds histogram" in text
        assert 'karpenter_filter_batch_seconds_bucket{stage="schedule"' in text
        assert "# TYPE karpenter_filter_fallback_total counter" in text
        assert 'karpenter_filter_fallback_total{reason="unsupported-operator"}' in text
        assert "# TYPE karpenter_filter_intern_table_size gauge" in text
        assert "karpenter_filter_intern_table_size{} 17" in text

    def test_engine_drives_the_metrics(self):
        """One scheduler window observes the histogram; compile updates the
        intern gauge."""
        from karpenter_tpu.api.constraints import Constraints
        from karpenter_tpu.api.core import NodeSelectorRequirement, Pod
        from karpenter_tpu.api.requirements import Requirements
        from karpenter_tpu.ops import feasibility
        from karpenter_tpu.runtime.kubecore import KubeCore
        from karpenter_tpu.scheduling.scheduler import Scheduler

        before = mfilter.FILTER_BATCH_SECONDS.collect().get(
            (("stage", "schedule"),), (None, 0.0, 0))[2]
        c = Constraints(requirements=Requirements().add(
            NodeSelectorRequirement(
                key="topology.kubernetes.io/zone", operator="In",
                values=["us-1a"])))
        pod = Pod()
        pod.spec.node_selector = {"topology.kubernetes.io/zone": "us-1a"}
        Scheduler(KubeCore())._get_schedules(c, [pod])
        after = mfilter.FILTER_BATCH_SECONDS.collect()[
            (("stage", "schedule"),)][2]
        assert after == before + 1
        feasibility.reset_intern_table()
        assert mfilter.FILTER_INTERN_TABLE_SIZE.collect()[()] == 0.0
