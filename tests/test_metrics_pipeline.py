"""Pipeline observability (metrics/pipeline.py + solver/pipeline.py).

Every series the round-7 pipeline promises must actually be emitted by a
run: the depth gauge, the per-stage histogram (marshal | device |
launch_bind), the overlap counter and the dispatch-queue wait histogram.
Driven with stub handles so the assertions are about the executor's
instrumentation, not the solver. The registry is process-wide, so counts
are asserted as deltas.
"""

import time

from karpenter_tpu.metrics.pipeline import (
    PIPELINE_DEPTH, PIPELINE_DISPATCH_WAIT_SECONDS, PIPELINE_STAGE_SECONDS,
    SOLVER_OVERLAP_SECONDS_TOTAL,
)
from karpenter_tpu.metrics.registry import DEFAULT
from karpenter_tpu.solver.pipeline import PipelineConfig, SolvePipeline


class FakeHandle:
    def __init__(self, results, wall_s=0.0):
        self._results = results
        self._wall_s = wall_s
        self.fetches = 0

    def fetch(self):
        self.fetches += 1
        if self._wall_s:
            time.sleep(self._wall_s)
        return self._results


class FakeMonitor:
    def __init__(self, level=0):
        self._level = level

    def level(self):
        return self._level


def _stage_totals():
    """{stage: observation count} snapshot of the stage histogram."""
    out = {}
    for lv, (_counts, _sum, total) in PIPELINE_STAGE_SECONDS.collect().items():
        out[dict(lv)["stage"]] = total
    return out


def _wait_total():
    data = PIPELINE_DISPATCH_WAIT_SECONDS.collect()
    return data.get((), (None, 0.0, 0))[2]


def _overlap_value():
    return SOLVER_OVERLAP_SECONDS_TOTAL.collect().get((), 0.0)


def _run(depth=2, chunks=(1, 2, 3), monitor=None):
    # adaptive pinned off: under a loaded host the controller may step the
    # depth mid-run, and these tests assert the gauge for a FIXED depth
    # (adaptive stepping has its own suite below)
    pipeline = SolvePipeline(PipelineConfig(depth=depth, chunk_items=0,
                                            adaptive=False),
                             monitor=monitor)
    return pipeline, pipeline.run(
        list(chunks),
        prepare=lambda c: c,
        dispatch=lambda prep: FakeHandle([prep]),
        consume=lambda prep, results: results[0])


class TestPipelineSeries:
    def test_depth_gauge_tracks_effective_depth(self):
        _run(depth=2)
        assert PIPELINE_DEPTH.collect()[()] == 2.0
        # L1+ pressure collapses the gauge (and the pipeline) to serial
        _run(depth=2, monitor=FakeMonitor(level=1))
        assert PIPELINE_DEPTH.collect()[()] == 1.0

    def test_stage_histogram_observes_every_stage_per_chunk(self):
        before = _stage_totals()
        _run(depth=2, chunks=range(3))
        after = _stage_totals()
        for stage in ("marshal", "device", "launch_bind"):
            assert after.get(stage, 0) - before.get(stage, 0) == 3, stage

    def test_dispatch_wait_histogram_observes_per_chunk(self):
        before = _wait_total()
        _run(depth=2, chunks=range(4))
        assert _wait_total() - before == 4

    def test_overlap_counter_accumulates_inflight_span(self):
        before = _overlap_value()
        pipeline = SolvePipeline(PipelineConfig(depth=2, chunk_items=0))
        pipeline.run(
            [0, 1],
            prepare=lambda c: c,
            dispatch=lambda prep: FakeHandle([prep]),
            # host work after dispatch: chunk 0's handle sits in flight
            # while chunk 1 marshals, so a real span accrues
            consume=lambda prep, results: time.sleep(0.02) or results[0])
        assert _overlap_value() > before

    def test_series_appear_in_prometheus_exposition(self):
        _run(depth=2)
        # counters only expose once incremented: drive one real ring fill
        # (an allocation) and one refill so the round-8 series carry samples
        import numpy as np

        from karpenter_tpu.parallel.mesh import batch_sharding, solver_mesh
        from karpenter_tpu.solver.pipeline import DeviceRing

        mesh = solver_mesh()
        ring = DeviceRing()
        host = np.zeros((mesh.devices.size, 2), np.int32)
        slot = ring.acquire(DeviceRing.signature({"x": host}))
        bs = batch_sharding(mesh)
        ring.fill(slot, "x", host, bs)
        ring.fill(slot, "x", host, bs)
        exposed = DEFAULT.expose()
        assert "karpenter_pipeline_depth{}" in exposed
        for stage in ("marshal", "device", "launch_bind"):
            assert (f'karpenter_pipeline_stage_seconds_count{{stage="{stage}"}}'
                    in exposed), stage
        assert "karpenter_solver_overlap_seconds_total{}" in exposed
        assert "karpenter_pipeline_dispatch_wait_seconds_count{}" in exposed
        # round-8 series: the ring's allocation ledger and the device
        # memory gauge (refreshed at the end of every run)
        assert "karpenter_solver_device_bytes_in_use{}" in exposed
        assert "karpenter_pipeline_ring_allocations_total{}" in exposed
        assert "karpenter_pipeline_ring_refills_total{}" in exposed

    def test_results_returned_in_chunk_order(self):
        _pipeline, outs = _run(depth=3, chunks=("a", "b", "c", "d"))
        assert outs == ["a", "b", "c", "d"]


class TestDeviceBytesGauge:
    def test_gauge_set_after_run(self):
        from karpenter_tpu.metrics.pipeline import SOLVER_DEVICE_BYTES_IN_USE
        from karpenter_tpu.solver.pipeline import observe_device_bytes

        total = observe_device_bytes()
        assert total >= 0
        assert SOLVER_DEVICE_BYTES_IN_USE.collect()[()] == float(total)
        # a run refreshes it too (the finally block), so the gauge is
        # never stale after a provisioning window
        _run(depth=2)
        assert SOLVER_DEVICE_BYTES_IN_USE.collect()[()] >= 0.0


class TestAdaptiveDepthGauge:
    def test_depth_gauge_follows_adaptive_collapse(self):
        """Windows whose overlap cannot pay (device answers instantly,
        host consume dominates) must step the ADAPTIVE depth down and the
        gauge must report the stepped value, not the configured flag."""
        pipeline = SolvePipeline(
            PipelineConfig(depth=2, chunk_items=0, adaptive=True))
        for _ in range(3):
            pipeline.run(
                [0, 1, 2],
                prepare=lambda c: c,
                # all the wall lands in the LAST chunk's blocking fetch —
                # nothing overlaps behind it, so overlap/wall < pay_frac
                dispatch=lambda prep: FakeHandle(
                    [prep], wall_s=0.05 if prep == 2 else 0.0),
                consume=lambda prep, results: results[0])
        assert pipeline.target_depth() == 1
        assert PIPELINE_DEPTH.collect()[()] == 1.0

    def test_pinned_config_never_steps(self):
        pipeline = SolvePipeline(
            PipelineConfig(depth=2, chunk_items=0, adaptive=False))
        for _ in range(3):
            pipeline.run(
                [0, 1, 2],
                prepare=lambda c: c,
                dispatch=lambda prep: FakeHandle(
                    [prep], wall_s=0.05 if prep == 2 else 0.0),
                consume=lambda prep, results: results[0])
        assert pipeline.target_depth() == 2
        assert PIPELINE_DEPTH.collect()[()] == 2.0
