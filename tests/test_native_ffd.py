"""Native C++ FFD kernel: build + differential parity.

The three executors over one encoded problem — device kernel, C++ kernel,
per-pod Python oracle — must agree on node counts for any workload
(solver/native_ffd.py header). Randomized differential tests mirror
tests/test_pack_parity.py's device-vs-oracle structure.
"""

import random

import pytest

from karpenter_tpu import native
from karpenter_tpu.cloudprovider.fake.provider import instance_types, make_instance_type
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.models.ffd import solve_ffd_numpy
from karpenter_tpu.solver import host_ffd
from karpenter_tpu.solver.adapter import build_packables, pod_vector
from karpenter_tpu.solver.native_ffd import (
    solve_ffd_native, solve_ffd_per_pod_native,
)

from tests.expectations import unschedulable_pod

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for the native kernel")


def _problem(pods, catalog):
    constraints = universe_constraints(catalog)
    packables, _ = build_packables(catalog, constraints, pods, [])
    vecs = [pod_vector(p) for p in pods]
    return vecs, list(range(len(pods))), packables


def _assert_parity(pods, catalog):
    vecs, ids, packables = _problem(pods, catalog)
    oracle = host_ffd.pack(vecs, ids, packables)
    nat = solve_ffd_native(vecs, ids, packables)
    assert nat is not None
    assert nat.node_count == oracle.node_count
    assert sorted(nat.unschedulable) == sorted(oracle.unschedulable)
    # every pod lands exactly once
    placed = sorted(pid for p in nat.packings for node in p.pod_ids for pid in node)
    expected = sorted(set(ids) - set(nat.unschedulable))
    assert placed == expected
    return nat


class TestNativeParity:
    def test_simple_workload(self):
        pods = [unschedulable_pod(requests={"cpu": "500m", "memory": "256Mi"})
                for _ in range(50)]
        _assert_parity(pods, instance_types(10))

    def test_mixed_shapes(self):
        shapes = [("100m", "64Mi"), ("1", "1Gi"), ("2", "512Mi"), ("250m", "4Gi")]
        pods = [unschedulable_pod(requests={"cpu": c, "memory": m})
                for i in range(200) for c, m in (shapes[i % 4],)]
        _assert_parity(pods, instance_types(20))

    def test_unschedulable_overflow(self):
        catalog = [make_instance_type("tiny", cpu="1", memory="1Gi", pods="10")]
        pods = [unschedulable_pod(requests={"cpu": "2", "memory": "512Mi"})]
        result = _assert_parity(pods, catalog)
        assert result.unschedulable == [0]

    def test_randomized_differential(self):
        rng = random.Random(20260729)
        for trial in range(10):
            n_types = rng.randint(1, 25)
            catalog = instance_types(n_types)
            pods = [
                unschedulable_pod(requests={
                    "cpu": f"{rng.choice([100, 250, 500, 1000, 2000, 4000])}m",
                    "memory": f"{rng.choice([64, 128, 512, 1024, 4096])}Mi",
                })
                for _ in range(rng.randint(1, 300))
            ]
            _assert_parity(pods, catalog)

    def test_matches_numpy_mirror_records(self):
        pods = [unschedulable_pod(requests={"cpu": "750m", "memory": "300Mi"})
                for _ in range(500)]
        vecs, ids, packables = _problem(pods, instance_types(15))
        nat = solve_ffd_native(vecs, ids, packables)
        npy = solve_ffd_numpy(vecs, ids, packables)
        assert nat.node_count == npy.node_count
        assert sorted(nat.unschedulable) == sorted(npy.unschedulable)

    def test_empty_packables(self):
        result = solve_ffd_native([(10**9, 0, 0, 0, 0, 0, 0, 0)], [0], [])
        assert result.node_count == 0
        assert result.unschedulable == [0]


def _result_key(r):
    return (
        sorted((tuple(p.instance_type_indices), p.node_quantity,
                sorted(tuple(sorted(n)) for n in p.pod_ids))
               for p in r.packings),
        sorted(r.unschedulable),
    )


class TestPerPodNativeOracle:
    """kt_ffd_pack_per_pod is a transcription of packer.go:109-141, not the
    shape-level greedy: it must reproduce the Python per-pod oracle to the
    FULL result key (per-node pod sets, option lists, quantities), since the
    bench's 50k-pod parity claim rests on it being genuinely per-pod."""

    def test_full_result_key_randomized(self):
        rng = random.Random(3_2026)
        for trial in range(15):
            catalog = instance_types(rng.randint(1, 25))
            pods = [
                unschedulable_pod(requests={
                    "cpu": f"{rng.choice([50, 100, 250, 500, 1000, 2000, 3000])}m",
                    "memory": f"{rng.choice([32, 64, 256, 512, 1024, 4096])}Mi",
                })
                for _ in range(rng.randint(1, 300))
            ]
            vecs, ids, packables = _problem(pods, catalog)
            want = host_ffd.pack(vecs, ids, packables)
            got = solve_ffd_per_pod_native(vecs, ids, packables)
            assert got is not None
            assert _result_key(got) == _result_key(want), f"trial {trial}"

    def test_agrees_with_fast_forward_executors(self):
        # independent algorithms, same node count (the ±1 target, held exact)
        pods = [unschedulable_pod(requests={"cpu": f"{c}m", "memory": f"{m}Mi"})
                for c, m in [(100, 128), (500, 512), (1500, 1024), (4000, 4096)]
                for _ in range(250)]
        vecs, ids, packables = _problem(pods, instance_types(20))
        per_pod = solve_ffd_per_pod_native(vecs, ids, packables)
        shape_level = solve_ffd_native(vecs, ids, packables)
        numpy_mirror = solve_ffd_numpy(vecs, ids, packables)
        assert per_pod.node_count == shape_level.node_count == numpy_mirror.node_count

    def test_unschedulable_single_drop(self):
        catalog = [make_instance_type("tiny", cpu="1", memory="1Gi", pods="10")]
        pods = [unschedulable_pod(requests={"cpu": "2", "memory": "512Mi"}),
                unschedulable_pod(requests={"cpu": "500m", "memory": "128Mi"})]
        vecs, ids, packables = _problem(pods, catalog)
        got = solve_ffd_per_pod_native(vecs, ids, packables)
        want = host_ffd.pack(vecs, ids, packables)
        assert _result_key(got) == _result_key(want)
        assert got.unschedulable == [0]


class TestRecordBufferBound:
    """Fuzz-soak find (2,000-case run, case 1897): the shape-level C++
    kernel's record buffer was capped by a min() with an S*T-derived term
    that was meant as generosity for tiny problems but became a CAP — at
    2 shapes x 2 types with 227 pods the solve needs ~115 records, the cap
    allowed 32, the kernel reported overflow and silently declined
    (production fell through to the per-pod ring; the shape-level executor
    was just unavailable in a regime it should own). The bound is now
    pods + S + slack under a memory-budget clamp. This test replays the
    exact found case from the fuzz RNG stream and ASSERTS the regime still
    holds, so retuning the fuzz pools cannot quietly turn it into a
    generic parity check."""

    def test_many_records_at_tiny_shape_type_cardinality(self):
        import random

        from karpenter_tpu.ops.encode import encode
        from tests.test_fuzz_parity import (
            _random_catalog, _random_daemons, _random_pods,
        )

        rng = random.Random(20260729)  # the fuzz seed
        for case in range(1898):       # walk the stream to case 1897
            catalog = _random_catalog(rng)
            pods = _random_pods(rng)
            daemons = _random_daemons(rng)
        constraints = universe_constraints(catalog)
        packables, _ = build_packables(catalog, constraints, pods, daemons)
        vecs = [pod_vector(p) for p in pods]
        ids = list(range(len(pods)))
        # regime canary: node count exceeding the OLD min(4*S*T, pods+S)+16
        # cap is what made case 1897 overflow (115 nodes vs cap 32; records
        # <= nodes, and here the fast-forward collapsed almost nothing). If
        # the fuzz pools are ever retuned, the RNG stream shifts and this
        # trips instead of silently degrading into a generic parity check.
        enc = encode(vecs, ids, packables, pad=False)
        assert enc is not None
        S, T = enc.num_shapes, enc.num_types
        old_cap = min(4 * S * max(T, 1), len(pods) + S) + 16
        oracle = host_ffd.pack(vecs, ids, packables)
        total_nodes = sum(p.node_quantity for p in oracle.packings)
        assert total_nodes > old_cap, (
            f"fuzz pools retuned: case 1897 no longer exercises the "
            f"record-cap regime ({total_nodes} nodes <= old cap "
            f"{old_cap}) — re-derive the case or pin it literally")
        got = solve_ffd_native(vecs, ids, packables)
        assert got is not None, (
            "shape-level kernel declined a tiny-S*T many-record problem "
            "(record-buffer cap regression)")
        key = lambda r: (r.node_count, sorted(r.unschedulable),
                         sorted((tuple(p.instance_type_indices),
                                 p.node_quantity) for p in r.packings))
        assert key(got) == key(oracle)
