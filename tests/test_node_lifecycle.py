"""Node lifecycle: readiness/liveness/expiration/emptiness/finalizer.

Mirrors pkg/controllers/node/suite_test.go using the injectable clock.
"""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import (
    Node, NodeCondition, NodeSpec, NodeStatus, ObjectMeta, OwnerReference,
    Pod, PodSpec, Taint,
)
from karpenter_tpu.controllers.node import (
    LIVENESS_TIMEOUT_SECONDS, NodeController,
)
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.utils import clock
from tests.expectations import make_provisioner


@pytest.fixture()
def env():
    kube = KubeCore()
    controller = NodeController(kube)
    clock.DEFAULT.set(1_000_000.0)
    return kube, controller


def make_node(name="node-1", provisioner="default", ready=True, taints=None,
              finalizers=None, creation=None):
    return Node(
        metadata=ObjectMeta(
            name=name, namespace="",
            labels={wellknown.PROVISIONER_NAME_LABEL: provisioner},
            finalizers=list(finalizers if finalizers is not None
                            else [wellknown.TERMINATION_FINALIZER]),
            creation_timestamp=creation,
        ),
        spec=NodeSpec(taints=list(taints or [])),
        status=NodeStatus(conditions=[NodeCondition(
            type="Ready", status="True" if ready else "False",
            reason="KubeletReady" if ready else "")]),
    )


def pod_on_node(kube, node_name, name="p1", daemonset=False):
    pod = Pod(metadata=ObjectMeta(name=name), spec=PodSpec(node_name=node_name))
    if daemonset:
        pod.metadata.owner_references.append(OwnerReference(kind="DaemonSet", name="ds"))
    kube.create(pod)
    return pod


class TestReadiness:
    def test_removes_not_ready_taint_when_ready(self, env):
        kube, controller = env
        kube.create(make_provisioner())
        node = make_node(ready=True, taints=[
            Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule"),
            Taint(key="other", value="v", effect="NoSchedule")])
        kube.create(node)
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert [t.key for t in stored.spec.taints] == ["other"]

    def test_keeps_taint_when_not_ready(self, env):
        kube, controller = env
        kube.create(make_provisioner())
        node = make_node(ready=False, taints=[
            Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule")])
        kube.create(node)
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert [t.key for t in stored.spec.taints] == [wellknown.NOT_READY_TAINT_KEY]


class TestLiveness:
    def test_deletes_node_that_never_joined(self, env):
        kube, controller = env
        kube.create(make_provisioner())
        node = make_node(ready=False, creation=clock.now())
        node.status.conditions = []  # kubelet never reported
        kube.create(node)
        clock.DEFAULT.advance(LIVENESS_TIMEOUT_SECONDS + 1)
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert stored.metadata.deletion_timestamp is not None

    def test_keeps_live_node(self, env):
        kube, controller = env
        kube.create(make_provisioner())
        node = make_node(ready=True, creation=clock.now())
        node.status.conditions[0].reason = "KubeletReady"
        kube.create(node)
        clock.DEFAULT.advance(LIVENESS_TIMEOUT_SECONDS + 1)
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert stored.metadata.deletion_timestamp is None


class TestExpiration:
    def test_expires_old_node(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_until_expired=30))
        kube.create(make_node(creation=clock.now()))
        clock.DEFAULT.advance(31)
        controller.reconcile("node-1")
        assert kube.get("Node", "node-1", "").metadata.deletion_timestamp is not None

    def test_keeps_young_node_with_requeue(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_until_expired=300))
        kube.create(make_node(creation=clock.now()))
        requeue = controller.reconcile("node-1")
        assert kube.get("Node", "node-1", "").metadata.deletion_timestamp is None
        assert requeue is not None and requeue <= 300

    def test_no_ttl_never_expires(self, env):
        kube, controller = env
        kube.create(make_provisioner())
        kube.create(make_node(creation=clock.now()))
        clock.DEFAULT.advance(10**6)
        controller.reconcile("node-1")
        assert kube.get("Node", "node-1", "").metadata.deletion_timestamp is None


class TestEmptiness:
    def test_stamps_and_deletes_empty_node(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_after_empty=30))
        kube.create(make_node(ready=True, creation=clock.now()))
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in stored.metadata.annotations
        clock.DEFAULT.advance(31)
        controller.reconcile("node-1")
        assert kube.get("Node", "node-1", "").metadata.deletion_timestamp is not None

    def test_daemonset_pods_count_as_empty(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_after_empty=30))
        kube.create(make_node(ready=True, creation=clock.now()))
        pod_on_node(kube, "node-1", daemonset=True)
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in stored.metadata.annotations

    def test_workload_pod_clears_stamp(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_after_empty=30))
        kube.create(make_node(ready=True, creation=clock.now()))
        controller.reconcile("node-1")
        assert wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in (
            kube.get("Node", "node-1", "").metadata.annotations)
        pod_on_node(kube, "node-1")
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert wellknown.EMPTINESS_TIMESTAMP_ANNOTATION not in stored.metadata.annotations
        assert stored.metadata.deletion_timestamp is None


class TestFinalizer:
    def test_readds_finalizer_to_self_registered_node(self, env):
        kube, controller = env
        kube.create(make_provisioner())
        kube.create(make_node(finalizers=[]))
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert wellknown.TERMINATION_FINALIZER in stored.metadata.finalizers

    def test_ignores_unmanaged_nodes(self, env):
        kube, controller = env
        node = make_node(finalizers=[])
        node.metadata.labels = {}  # no provisioner label
        kube.create(node)
        controller.reconcile("node-1")
        stored = kube.get("Node", "node-1", "")
        assert stored.metadata.finalizers == []


class TestControllerGates:
    """Cross-cutting controller behaviors (node/suite_test.go:74-360)."""

    def test_not_ready_node_never_gets_emptiness_ttl(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_after_empty=30))
        node = make_node(ready=False)
        kube.create(node)
        controller.reconcile(node.metadata.name)
        stored = kube.get("Node", node.metadata.name, "")
        assert wellknown.EMPTINESS_TIMESTAMP_ANNOTATION not in stored.metadata.annotations

    def test_ready_unknown_node_never_gets_emptiness_ttl(self, env):
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_after_empty=30))
        node = make_node()
        node.status.conditions[0].status = "Unknown"
        kube.create(node)
        controller.reconcile(node.metadata.name)
        stored = kube.get("Node", node.metadata.name, "")
        assert wellknown.EMPTINESS_TIMESTAMP_ANNOTATION not in stored.metadata.annotations

    def test_unmanaged_node_fully_ignored(self, env):
        """No provisioner label → none of the five sub-reconcilers touch it
        (controller.go:70-80)."""
        kube, controller = env
        kube.create(make_provisioner(ttl_seconds_after_empty=1,
                                     ttl_seconds_until_expired=1))
        node = make_node(name="byo", finalizers=[], taints=[
            Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule")])
        del node.metadata.labels[wellknown.PROVISIONER_NAME_LABEL]
        kube.create(node)
        clock.DEFAULT.advance(10_000)
        controller.reconcile("byo")
        stored = kube.get("Node", "byo", "")
        assert stored.metadata.finalizers == []            # no finalizer added
        assert any(t.key == wellknown.NOT_READY_TAINT_KEY  # taint untouched
                   for t in stored.spec.taints)

    def test_terminating_node_finalizer_not_readded(self, env):
        """finalizer.go: do nothing while terminating — re-adding would
        deadlock the termination controller's strip."""
        kube, controller = env
        kube.create(make_provisioner())
        node = make_node(name="dying")
        kube.create(node)
        kube.delete("Node", "dying", "")  # finalizer present → terminating
        controller.reconcile("dying")
        stored = kube.get("Node", "dying", "")
        assert stored.metadata.deletion_timestamp is not None
