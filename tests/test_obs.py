"""Observability layer (karpenter_tpu/obs/, ISSUE 9).

Covers: span parenting + cross-thread context carry, the disabled-mode
no-allocation guarantee, the flight recorder's trigger ring and tagged
dumps, span propagation across a pipeline fetch that trips the watchdog
mid-flight (the chaos leg — the dump names the poisoned window and no
problem is lost or duplicated), registry concurrency, /metrics help
rendering, /debug/vars, and the metrics lint.
"""

from __future__ import annotations

import gc
import json
import sys
import threading
import time

import pytest

from karpenter_tpu.metrics.registry import DEFAULT, Registry
from karpenter_tpu.obs import flight, trace


@pytest.fixture(autouse=True)
def _clean_obs():
    trace.disable()
    trace.reset()
    flight.reset()
    yield
    trace.disable()
    trace.reset()
    flight.reset()
    flight.configure(dir="", min_interval_s=5.0)


class TestTracerCore:
    def test_window_span_parents_children(self):
        trace.enable()
        with trace.window_span("provision", window_id="w-test-1",
                               shard="3", pressure_level=1) as w:
            with trace.span("feasibility", pods=10):
                pass
        spans = trace.snapshot()
        names = {s["name"]: s for s in spans}
        assert set(names) == {"provision", "feasibility"}
        child, root = names["feasibility"], names["provision"]
        assert root["trace_id"] == "w-test-1", \
            "window id IS the trace id (logs join on it)"
        assert child["trace_id"] == "w-test-1"
        assert child["parent_id"] == root["span_id"]
        assert root["tags"] == {"shard": "3", "pressure_level": 1}

    def test_context_carries_across_threads(self):
        """The dispatch/fetch split: a context captured at dispatch must
        reparent spans recorded by another thread entirely."""
        trace.enable()
        captured = {}
        with trace.window_span("provision", window_id="w-carry") as w:
            captured["ctx"] = trace.current_context()
        assert captured["ctx"] is w

        def fetch_side():
            with trace.use_context(captured["ctx"]):
                with trace.span("fetch"):
                    pass

        t = threading.Thread(target=fetch_side)
        t.start()
        t.join()
        fetch = [s for s in trace.snapshot() if s["name"] == "fetch"]
        assert len(fetch) == 1
        assert fetch[0]["trace_id"] == "w-carry"
        assert fetch[0]["parent_id"] == w.span_id

    def test_disabled_is_noop_singleton(self):
        assert not trace.enabled()
        s1 = trace.span("anything", k=1)
        s2 = trace.window_span("provision")
        assert s1 is s2, "disabled mode must hand back one shared no-op"
        with s1 as inner:
            assert inner.trace_id is None
        trace.add_span("retro", 0.0, 1.0)
        trace.event("instant")
        assert trace.snapshot() == []
        assert trace.current_context() is None

    def test_window_ids_unique_even_disabled(self):
        ids = {trace.new_window_id() for _ in range(100)}
        assert len(ids) == 100
        assert all(i.startswith("w-") for i in ids)

    def test_chrome_events_shapes(self):
        trace.enable()
        with trace.window_span("provision", window_id="w-chrome"):
            trace.event("ring-refill", buffer="pods")
        evs = trace.chrome_events()
        by_name = {e["name"]: e for e in evs}
        assert by_name["provision"]["ph"] == "X"
        assert by_name["provision"]["dur"] >= 0
        assert by_name["ring-refill"]["ph"] == "i"
        assert by_name["ring-refill"]["args"]["trace_id"] == "w-chrome"
        assert by_name["ring-refill"]["args"]["buffer"] == "pods"

    def test_dump_chrome_roundtrip(self, tmp_path):
        trace.enable()
        with trace.window_span("provision", window_id="w-dump"):
            with trace.span("marshal"):
                pass
        path = trace.dump_chrome(str(tmp_path / "trace.json"))
        payload = json.loads(open(path).read())
        assert len(payload["traceEvents"]) == 2
        assert payload["otherData"]["spans"]["enabled"] is True

    def test_jax_annotations_mode_records_normally(self):
        """--trace-jax: spans also enter jax.profiler.TraceAnnotation;
        recording must be unaffected (and never crash if jax is odd)."""
        trace.enable(jax_annotations=True)
        with trace.window_span("provision", window_id="w-jax"):
            with trace.span("device_solve"):
                pass
        names = {s["name"] for s in trace.snapshot()}
        assert names == {"provision", "device_solve"}
        assert trace.state()["jax_annotations"] is True

    def test_measure_overhead_restores_state(self):
        out = trace.measure_overhead(n=2_000)
        assert out["disabled_ns_per_span"] < out["enabled_ns_per_span"]
        assert not trace.enabled(), "measure must restore prior state"
        assert trace.snapshot() == [], "probe spans must be dropped"


class TestDisabledModeAllocations:
    def test_no_steady_state_allocations(self):
        """The ISSUE 9 acceptance bound: disabled tracing must not grow
        the heap per call — span() hands back a preallocated singleton."""
        assert not trace.enabled()
        # warm any lazy interning (method wrappers, thread-local slot)
        for _ in range(200):
            with trace.span("steady"):
                pass
            trace.event("steady")
        gc.collect()
        before = sys.getallocatedblocks()
        for _ in range(10_000):
            with trace.span("steady"):
                pass
            trace.event("steady")
        after = sys.getallocatedblocks()
        # unrelated interpreter activity wiggles a handful of blocks; a
        # per-call allocation would show up as >= 10k
        assert after - before < 100, (
            f"disabled tracer allocated {after - before} blocks / 10k spans")


class TestFlightRecorder:
    def test_trip_without_dir_stays_in_memory(self):
        path = flight.trip("watchdog-trip", reason="test")
        assert path is None
        recent = flight.recent()
        assert recent[-1]["trigger"] == "watchdog-trip"
        assert recent[-1]["tags"]["reason"] == "test"
        st = flight.state()
        assert st["trips"] == 1 and st["dumps_written"] == 0

    def test_trip_with_dir_writes_tagged_dump(self, tmp_path):
        trace.enable()
        flight.configure(dir=str(tmp_path), min_interval_s=0.0)
        with trace.window_span("provision", window_id="w-flight"):
            with trace.span("fetch"):
                pass
            path = flight.trip("pressure-l3", from_level=2)
        assert path is not None and "pressure-l3" in path
        payload = json.loads(open(path).read())
        assert payload["trigger"] == "pressure-l3"
        assert payload["tags"]["from_level"] == 2
        assert payload["tags"]["trace_id"] == "w-flight", \
            "the active window's trace id must ride along automatically"
        # the ring snapshot carries the spans finished so far
        assert any(e.get("name") == "fetch" for e in payload["events"])

    def test_rate_limit_suppresses_dump_not_record(self, tmp_path):
        flight.configure(dir=str(tmp_path), min_interval_s=60.0)
        first = flight.trip("chaos-fault", kind="a")
        second = flight.trip("chaos-fault", kind="b")
        assert first is not None and second is None
        assert len(flight.recent()) == 2, \
            "rate limiting must only skip the file write"


class TestWatchdogTripSpanPropagation:
    """The chaos leg: a pipeline fetch that trips the watchdog mid-flight
    must (a) surface the poisoned window's trace id in the flight dump
    and (b) lose/duplicate nothing — fallback answers stay complete."""

    @pytest.fixture()
    def fresh_watchdog(self, monkeypatch):
        from karpenter_tpu.solver import solve as solve_mod
        from karpenter_tpu.solver.solve import _DeviceWatchdog

        wd = _DeviceWatchdog()
        monkeypatch.setattr(solve_mod, "_WATCHDOG", wd)
        return wd

    def _problems(self, n_problems=3, pods_each=30):
        from karpenter_tpu.cloudprovider.fake.provider import instance_types
        from karpenter_tpu.controllers.provisioning import universe_constraints
        from karpenter_tpu.solver.batch_solve import Problem
        from tests.expectations import unschedulable_pod

        catalog = instance_types(6)
        constraints = universe_constraints(catalog)
        return [
            Problem(constraints=constraints,
                    pods=[unschedulable_pod(requests={"cpu": "500m"})
                          for _ in range(pods_each)],
                    instance_types=catalog)
            for _ in range(n_problems)
        ]

    def test_fetch_trip_dump_names_poisoned_window(self, fresh_watchdog,
                                                   monkeypatch, tmp_path):
        from karpenter_tpu.solver import batch_solve as bs
        from karpenter_tpu.solver.batch_solve import dispatch_batch, solve_batch
        from karpenter_tpu.solver.solve import SolverConfig

        problems = self._problems()
        want = solve_batch(problems, config=SolverConfig(use_device=False))

        trace.enable()
        flight.configure(dir=str(tmp_path), min_interval_s=0.0)

        # hang at the fetch seam (the materialize), exactly where a sick
        # transport stalls — dispatch itself stays healthy
        monkeypatch.setattr(bs, "_finish_device_batch",
                            lambda *a, **kw: time.sleep(10.0))
        wid = trace.new_window_id()
        cfg = SolverConfig(device_min_pods=1, device_timeout_s=0.1,
                           device_breaker_seconds=30.0, use_native=False)
        with trace.window_span("provision", window_id=wid):
            handle = dispatch_batch(problems, cfg)

        # fetch on a DIFFERENT thread with no active span: the handle's
        # captured context is the only way the trip can know its window
        out = {}

        def fetch_side():
            out["results"] = handle.fetch()

        t = threading.Thread(target=fetch_side)
        t.start()
        t.join(timeout=30.0)
        assert not t.is_alive(), "fetch stalled behind the hung device call"
        assert fresh_watchdog.tripped()

        # (b) nothing lost, nothing duplicated: every problem answered
        # once, node-for-node equal to the host baseline
        got = out["results"]
        assert len(got) == len(problems)
        assert [r.node_count for r in got] == [r.node_count for r in want]

        # (a) the flight dump is tagged with the trigger AND the poisoned
        # window's trace id, carried dispatch -> cross-thread fetch
        trips = [r for r in flight.recent()
                 if r["trigger"] == "watchdog-trip"]
        assert len(trips) == 1, "exactly one trip, no duplicates"
        assert trips[0]["tags"]["trace_id"] == wid
        assert trips[0]["tags"]["reason"] == "run-expired"
        dumps = flight.recent_dumps()
        assert len(dumps) == 1
        payload = json.loads(open(dumps[0]).read())
        assert payload["trigger"] == "watchdog-trip"
        assert payload["tags"]["trace_id"] == wid
        # the fetch span itself is in the buffered spans under the window
        fetch_spans = [s for s in trace.snapshot()
                       if s["name"] == "fetch" and s["trace_id"] == wid]
        assert len(fetch_spans) == 1

    def test_seeded_chaos_trip_is_tagged(self, fresh_watchdog, tmp_path):
        """A chaos-injected watchdog trip (FaultPlan, seeded) must write a
        dump tagged with both the chaos fault and the watchdog trigger."""
        from karpenter_tpu.chaos import inject
        from karpenter_tpu.solver.batch_solve import solve_batch
        from karpenter_tpu.solver.solve import SolverConfig

        problems = self._problems()
        want = solve_batch(problems, config=SolverConfig(use_device=False))

        trace.enable()
        flight.configure(dir=str(tmp_path), min_interval_s=0.0)
        plan = inject.FaultPlan(11, [
            inject.FaultSpec("device", "solve", "watchdog-trip", 1)],
            window=1)
        inject.install(plan)
        wid = trace.new_window_id()
        try:
            with trace.window_span("provision", window_id=wid):
                got = solve_batch(problems, config=SolverConfig(
                    device_min_pods=1, device_timeout_s=5.0,
                    device_breaker_seconds=0.2, use_native=False))
        finally:
            inject.uninstall()
        assert plan.fired_counts() == {
            ("device", "solve", "watchdog-trip"): 1}
        assert [r.node_count for r in got] == [r.node_count for r in want]
        triggers = [r["trigger"] for r in flight.recent()]
        assert "chaos-fault" in triggers
        wd_trips = [r for r in flight.recent()
                    if r["trigger"] == "watchdog-trip"]
        assert len(wd_trips) == 1
        assert wd_trips[0]["tags"]["reason"] == "injected"
        assert wd_trips[0]["tags"]["trace_id"] == wid


class TestRegistryConcurrency:
    def test_parallel_inc_and_observe_exact(self):
        """Shard workers hammer one registry concurrently; totals must be
        exact (no lost updates under the GIL's preemption points)."""
        reg = Registry()
        counter = reg.counter("obs_smoke_total", "concurrency smoke")
        hist = reg.histogram("obs_smoke_seconds", "concurrency smoke")
        workers, per = 8, 2_000
        start = threading.Barrier(workers)

        def worker(i):
            start.wait()
            for k in range(per):
                counter.inc(shard=str(i % 2))
                hist.observe(0.01 * (k % 7), exemplar=f"w-{i}-{k}",
                             shard=str(i % 2))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        total = sum(counter.collect().values())
        assert total == workers * per
        hist_total = sum(tot for _, _, tot in hist.collect().values())
        assert hist_total == workers * per
        # each series kept exactly one (latest-wins) exemplar
        for lv, ex in hist.collect_exemplars().items():
            assert ex["trace_id"].startswith("w-")

    def test_histogram_exemplar_surfaces_in_snapshot_not_text(self):
        reg = Registry()
        hist = reg.histogram("obs_exemplar_seconds", "exemplar smoke")
        hist.observe(0.2, exemplar="w-ex-1", provisioner="default")
        text = reg.expose()
        assert "w-ex-1" not in text, \
            "exemplars must stay out of the Prometheus text format"
        snap = reg.snapshot()
        series = snap["obs_exemplar_seconds"]["series"]
        (entry,) = series.values()
        assert entry["count"] == 1
        assert entry["exemplar"]["trace_id"] == "w-ex-1"


class TestMetricsEndpointAndLint:
    def test_every_registered_series_renders_with_help(self):
        from tools.metrics_lint import REGISTERING_MODULES
        import importlib

        for mod in REGISTERING_MODULES:
            importlib.import_module(mod)
        exposed = DEFAULT.expose()
        registered = DEFAULT.registered()
        assert registered, "no metrics registered?"
        for name, metric in sorted(registered.items()):
            assert metric.help, f"{name} lacks help text"
            assert f"# HELP karpenter_{name} {metric.help}" in exposed, \
                f"{name} renders without its HELP line"

    def test_metrics_lint_passes(self):
        from tools.metrics_lint import lint

        assert lint() == []

    def test_lint_import_list_matches_registration_sites(self):
        """Keep tools/metrics_lint.py's module list honest: every file
        registering a metric at import time must be on it."""
        import os
        import re

        from tools.metrics_lint import REGISTERING_MODULES

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pat = re.compile(r"\.(?:gauge|counter|histogram)\(")
        found = set()
        pkg = os.path.join(root, "karpenter_tpu")
        for dirpath, _, files in os.walk(pkg):
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                mod = rel[:-3].replace(os.sep, ".")
                if mod == "karpenter_tpu.metrics.registry":
                    continue  # defines the registry, registers nothing
                with open(path) as f:
                    if pat.search(f.read()):
                        found.add(mod)
        missing = found - set(REGISTERING_MODULES)
        assert not missing, (
            f"metric registration sites missing from metrics_lint: {missing}")


class TestTraceview:
    def test_analyze_critical_path_and_overlap(self):
        """Synthetic window: intake 0-100ms, device_solve 100-300ms,
        launch_bind 200-400ms — 100ms of genuine overlap, and the
        sweep-line charges device_solve only its un-hidden 100ms."""
        from tools.traceview import analyze

        def x(name, t0_ms, t1_ms, **args):
            return {"name": name, "ph": "X", "ts": t0_ms * 1000.0,
                    "dur": (t1_ms - t0_ms) * 1000.0, "pid": 1, "tid": 1,
                    "args": {"trace_id": "w-tv", **args}}

        events = [
            x("provision", 0, 400, shard="0"),
            x("intake", 0, 100, parent_id=1),
            x("device_solve", 100, 300, parent_id=1),
            x("launch_bind", 200, 400, parent_id=1),
        ]
        (r,) = analyze(events)
        assert r["window"] == "w-tv" and r["kind"] == "provision"
        assert r["wall_s"] == pytest.approx(0.4)
        assert r["overlap_s"] == pytest.approx(0.1)
        assert r["coverage"] == pytest.approx(1.0)
        assert r["stages"]["device_solve"] == pytest.approx(0.2)
        crit = r["critical_path"]
        # launch_bind starts later, so it owns 200-400; device_solve only
        # its exclusive 100-200 slice
        assert crit["device_solve"] == pytest.approx(0.1)
        assert crit["launch_bind"] == pytest.approx(0.2)
        assert crit["intake"] == pytest.approx(0.1)
        assert sum(crit.values()) == pytest.approx(0.4), \
            "exclusive times must tile the covered window"

    def test_real_dump_roundtrips_through_traceview(self, tmp_path):
        from tools.traceview import analyze

        trace.enable()
        wid = trace.new_window_id()
        with trace.window_span("provision", window_id=wid, shard="1"):
            with trace.span("intake"):
                time.sleep(0.002)
            with trace.span("device_solve"):
                time.sleep(0.002)
        path = trace.dump_chrome(str(tmp_path / "t.json"))
        events = json.loads(open(path).read())["traceEvents"]
        (r,) = analyze(events)
        assert r["window"] == wid
        assert set(r["stages"]) == {"intake", "device_solve"}
        assert r["overlap_s"] == pytest.approx(0.0, abs=1e-6)
        assert 0 < r["coverage"] <= 1.0

    def test_render_shows_slo_digest_columns(self, tmp_path):
        """A dump taken with live SLO digests carries them in otherData;
        render() must grow slo_p50/slo_p99 columns mapped onto the trace
        stages plus the cumulative footer."""
        import io

        from karpenter_tpu.obs import slo
        from tools.traceview import analyze, render

        slo.reset()
        slo.enable()
        try:
            slo.record("default", "intake", 0.05, count=100)
            slo.record("default", "solve", 0.2, count=100)
            trace.enable()
            wid = trace.new_window_id()
            with trace.window_span("provision", window_id=wid):
                with trace.span("intake"):
                    time.sleep(0.002)
                with trace.span("device_solve"):
                    time.sleep(0.002)
            path = trace.dump_chrome(str(tmp_path / "t.json"))
            dump = json.loads(open(path).read())
            assert dump["otherData"]["slo"]["records"] == 200
            buf = io.StringIO()
            render(analyze(dump["traceEvents"]), out=buf,
                   slo=dump["otherData"]["slo"])
            text = buf.getvalue()
            assert "slo_p50" in text and "slo_p99" in text
            assert "slo digests (cumulative" in text
            # device_solve row maps to the 'solve' digest (~0.2s)
            solve_row = next(line for line in text.splitlines()
                             if line.strip().startswith("device_solve"))
            assert "0.2" in solve_row, solve_row
            # without a snapshot the table keeps its old shape
            buf2 = io.StringIO()
            render(analyze(dump["traceEvents"]), out=buf2)
            assert "slo_p50" not in buf2.getvalue()
        finally:
            slo.reset()


class TestDebugVars:
    def test_payload_shape_and_serializable(self):
        from karpenter_tpu.main import debug_vars

        payload = debug_vars()
        assert set(payload) >= {"metrics", "pressure", "solver", "ring",
                                "trace", "flight"}
        json.dumps(payload, default=str)
        assert payload["trace"]["enabled"] in (True, False)
        assert "trips" in payload["flight"]

    def test_http_endpoints(self):
        """GET /metrics and /debug/vars off the real handler."""
        import urllib.request
        from http.server import ThreadingHTTPServer

        from karpenter_tpu import main as main_mod

        handler = type("H", (main_mod._Handler,), {"manager": None})
        server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                text = r.read().decode()
            assert "# HELP karpenter_" in text
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/vars", timeout=10) as r:
                payload = json.loads(r.read().decode())
            assert "metrics" in payload and "flight" in payload
        finally:
            server.shutdown()
