"""Pipeline/warmup option plumbing (config/options.py).

The round-7 flags must parse from the CLI, fall back to their
KARPENTER_-prefixed environment variables, let an explicit flag beat the
environment, and validate their ranges — an operator typo must fail at
boot, not deep in the hot loop.
"""

import pytest

from karpenter_tpu.config.options import Options, parse


class TestDefaults:
    def test_pipeline_and_warmup_defaults(self):
        o = parse([])
        assert o.pipeline_depth == 2
        assert o.pipeline_chunk_items == 4096
        assert o.solver_warmup is False
        assert o.solver_compile_cache_dir == ""


class TestFlags:
    def test_flags_parse(self):
        o = parse([
            "--pipeline-depth", "3",
            "--pipeline-chunk-items", "512",
            "--solver-warmup",
            "--solver-compile-cache-dir", "/tmp/ktpu-cache",
        ])
        assert o.pipeline_depth == 3
        assert o.pipeline_chunk_items == 512
        assert o.solver_warmup is True
        assert o.solver_compile_cache_dir == "/tmp/ktpu-cache"

    def test_no_solver_warmup_flag(self):
        assert parse(["--no-solver-warmup"]).solver_warmup is False


class TestEnvFallback:
    def test_env_fallbacks(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_PIPELINE_DEPTH", "4")
        monkeypatch.setenv("KARPENTER_PIPELINE_CHUNK_ITEMS", "128")
        monkeypatch.setenv("KARPENTER_SOLVER_WARMUP", "true")
        monkeypatch.setenv("KARPENTER_SOLVER_COMPILE_CACHE_DIR", "/var/cache/xla")
        o = parse([])
        assert o.pipeline_depth == 4
        assert o.pipeline_chunk_items == 128
        assert o.solver_warmup is True
        assert o.solver_compile_cache_dir == "/var/cache/xla"

    def test_flag_beats_env(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_PIPELINE_DEPTH", "4")
        assert parse(["--pipeline-depth", "5"]).pipeline_depth == 5

    def test_no_flag_beats_env_bool(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOLVER_WARMUP", "true")
        assert parse(["--no-solver-warmup"]).solver_warmup is False

    @pytest.mark.parametrize("raw,want", [
        ("1", True), ("yes", True), ("TRUE", True),
        ("0", False), ("false", False), ("", False),
    ])
    def test_bool_env_coercion(self, monkeypatch, raw, want):
        monkeypatch.setenv("KARPENTER_SOLVER_WARMUP", raw)
        assert parse([]).solver_warmup is want


class TestValidation:
    def _errs(self, **kw):
        return Options(cluster_name="c", cluster_endpoint="e", **kw).validate()

    def test_valid_defaults_pass(self):
        assert self._errs() == []

    def test_pipeline_depth_must_be_positive(self):
        errs = self._errs(pipeline_depth=0)
        assert any("pipeline-depth" in e for e in errs)

    def test_pipeline_chunk_items_must_be_nonnegative(self):
        errs = self._errs(pipeline_chunk_items=-1)
        assert any("pipeline-chunk-items" in e for e in errs)

    def test_zero_chunk_items_disables_chunking_and_is_valid(self):
        assert self._errs(pipeline_chunk_items=0) == []


class TestSloOptions:
    def test_defaults(self):
        o = parse([])
        assert o.slo_enabled is True
        assert o.slo_objectives == ""
        assert o.slo_fast_window_seconds == 60.0
        assert o.slo_slow_window_seconds == 1800.0
        assert (o.slo_fast_burn, o.slo_slow_burn) == (6.0, 1.0)

    def test_objectives_parse_with_optional_target(self):
        o = parse(["--slo-objectives",
                   "default=30,high=20:0.995, system-critical = 10"])
        assert o.parse_slo_objectives() == {
            "default": (30.0, 0.99),
            "high": (20.0, 0.995),
            "system-critical": (10.0, 0.99)}

    def test_flags_and_env(self, monkeypatch):
        assert parse(["--no-slo-enabled"]).slo_enabled is False
        monkeypatch.setenv("KARPENTER_SLO_OBJECTIVES", "default=45")
        assert parse([]).parse_slo_objectives() == {"default": (45.0, 0.99)}

    def test_malformed_objectives_fail_validation(self):
        def errs(**kw):
            return Options(cluster_name="c", cluster_endpoint="e",
                           **kw).validate()
        assert any("slo-objectives" in e
                   for e in errs(slo_objectives="default=abc"))
        assert any("slo-objectives" in e
                   for e in errs(slo_objectives="default=30:1.5"))
        assert any("slo-objectives" in e
                   for e in errs(slo_objectives="default=-1"))
        assert any("slo-fast/slow-window" in e
                   for e in errs(slo_fast_window_seconds=0.0))
