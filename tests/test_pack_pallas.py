"""Pallas pack kernel ≡ XLA pack kernel ≡ host oracle.

The Pallas kernel (ops/pack_pallas.py) must produce the same committed node
records (chosen, q, packed), final counts/dropped, and done flag as the XLA
scan kernel (ops/pack.py) — junk rows (q == 0) excluded, since the scan
version reports stale values there by design. Runs in interpreter mode on
the CPU test mesh.
"""

import random

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake.provider import instance_types, make_instance_type
from karpenter_tpu.ops.encode import encode
from karpenter_tpu.ops.pack import pack_chunk
from karpenter_tpu.ops.pack_pallas import pack_chunk_pallas
from karpenter_tpu.solver import host_ffd
from karpenter_tpu.solver.adapter import build_packables, pod_vector
from tests.test_pack_parity import allow_all_constraints, make_pod


def encode_pods(pods, catalog):
    constraints = allow_all_constraints(catalog)
    packables, _ = build_packables(catalog, constraints, pods, [])
    vecs = [pod_vector(p) for p in pods]
    ids = list(range(len(pods)))
    enc = encode(vecs, ids, packables)
    assert enc is not None
    host = host_ffd.pack(vecs, ids, packables)
    return enc, host


def run_both(enc, num_iters=64):
    import jax.numpy as jnp

    from karpenter_tpu.ops.pack_pallas import check_counts_within_div_cap

    # counts is still concrete here — enforce the kernel's DIV_CAP
    # precondition instead of silently comparing clamped outputs
    check_counts_within_div_cap(enc.counts)
    args = (
        jnp.asarray(enc.shapes), jnp.asarray(enc.counts),
        jnp.zeros_like(jnp.asarray(enc.counts)), jnp.asarray(enc.totals),
        jnp.asarray(enc.reserved0), jnp.asarray(enc.valid),
        jnp.asarray(enc.last_valid, jnp.int32),
        jnp.asarray(enc.pods_unit, jnp.int32),
    )
    xla = pack_chunk(*args, num_iters=num_iters)
    pls = pack_chunk_pallas(*args, num_iters=num_iters, interpret=True)
    return [np.asarray(x) for x in xla], [np.asarray(x) for x in pls]


def committed(counts, dropped, done, chosen, q, packed):
    recs = [(int(chosen[i]), int(q[i]), tuple(int(v) for v in packed[i]))
            for i in range(len(q)) if q[i] > 0]
    return recs, counts.tolist(), dropped.tolist(), bool(done)


def assert_kernel_parity(enc, num_iters=64):
    xla, pls = run_both(enc, num_iters)
    assert committed(*pls) == committed(*xla)
    return pls


class TestPallasParity:
    def test_homogeneous(self):
        catalog = instance_types(6)
        pods = [make_pod({"cpu": "500m", "memory": "256Mi"}) for _ in range(40)]
        enc, host = encode_pods(pods, catalog)
        pls = assert_kernel_parity(enc)
        node_count = int(pls[4][pls[4] > 0].sum())
        assert node_count == host.node_count

    def test_mixed_with_drop(self):
        catalog = instance_types(3)
        pods = (
            [make_pod({"cpu": "250m", "memory": "128Mi"}) for _ in range(20)]
            + [make_pod({"cpu": "1", "memory": "9Gi"}) for _ in range(3)]
            + [make_pod({"cpu": "64", "memory": "1Gi"}) for _ in range(2)]  # drops
        )
        enc, host = encode_pods(pods, catalog)
        pls = assert_kernel_parity(enc)
        assert int(pls[1].sum()) == len(host.unschedulable)
        assert bool(pls[2])

    def test_gpu_exclusive_types(self):
        catalog = instance_types(4)
        catalog.append(make_instance_type(
            "gpu-big", cpu="16", memory="32Gi", pods="40", nvidia_gpus="8"))
        pods = [make_pod({"cpu": "1", "memory": "1Gi", "nvidia.com/gpu": "1"})
                for _ in range(6)]
        pods += [make_pod({"cpu": "500m", "memory": "512Mi"}) for _ in range(10)]
        enc, host = encode_pods(pods, catalog)
        pls = assert_kernel_parity(enc)
        node_count = int(pls[4][pls[4] > 0].sum())
        assert node_count == host.node_count

    def test_empty_counts_done_immediately(self):
        catalog = instance_types(2)
        pods = [make_pod({"cpu": "100m", "memory": "64Mi"})]
        enc, _ = encode_pods(pods, catalog)
        enc.counts[:] = 0
        xla, pls = run_both(enc, num_iters=8)
        assert bool(pls[2]) and committed(*pls) == committed(*xla)
        assert not pls[4].any()

    def test_chunking_resume(self):
        """A tiny num_iters forces done=False; resuming from the returned
        counts must agree with the XLA kernel's resume."""
        catalog = instance_types(8)
        pods = [make_pod({"cpu": f"{c}m", "memory": f"{m}Mi"})
                for c in (250, 500, 1000, 2000) for m in (128, 512, 1024)
                for _ in range(9)]
        enc, host = encode_pods(pods, catalog)
        import jax.numpy as jnp

        args = lambda counts, dropped: (
            jnp.asarray(enc.shapes), jnp.asarray(counts),
            jnp.asarray(dropped), jnp.asarray(enc.totals),
            jnp.asarray(enc.reserved0), jnp.asarray(enc.valid),
            jnp.asarray(enc.last_valid, jnp.int32),
            jnp.asarray(enc.pods_unit, jnp.int32),
        )
        total_nodes, counts, dropped = 0, enc.counts, np.zeros_like(enc.counts)
        for _ in range(64):
            out = pack_chunk_pallas(*args(counts, dropped), num_iters=2,
                                    interpret=True)
            counts, dropped, done, chosen, q, packed = map(np.asarray, out)
            total_nodes += int(q[q > 0].sum())
            if done:
                break
        assert done
        assert total_nodes == host.node_count

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_vs_xla_kernel(self, seed):
        rng = random.Random(1000 + seed)
        catalog = instance_types(rng.randint(1, 20))
        shapes = [{
            "cpu": f"{rng.choice([100, 250, 500, 1000, 2000, 64000])}m",
            "memory": f"{rng.choice([64, 256, 1024, 4096])}Mi",
        } for _ in range(rng.randint(1, 6))]
        pods = [make_pod(dict(rng.choice(shapes)))
                for _ in range(rng.randint(1, 300))]
        enc, host = encode_pods(pods, catalog)
        pls = assert_kernel_parity(enc)
        node_count = int(pls[4][pls[4] > 0].sum())
        assert node_count == host.node_count
        assert int(pls[1].sum()) == len(host.unschedulable)


class TestPallasSolvePath:
    def test_solve_ffd_device_pallas_kernel_matches_host(self):
        """Full solve_ffd_device flow on the pallas kernel (interpret mode
        off-TPU): same packings as the host oracle and the XLA kernel."""
        from karpenter_tpu.models.ffd import solve_ffd_device
        from karpenter_tpu.solver.adapter import build_packables, pod_vector

        catalog = instance_types(8)
        pods = [make_pod({"cpu": f"{c}m", "memory": f"{m}Mi"})
                for c in (250, 500, 2000) for m in (128, 1024) for _ in range(7)]
        constraints = allow_all_constraints(catalog)
        packables, _ = build_packables(catalog, constraints, pods, [])
        vecs = [pod_vector(p) for p in pods]
        ids = list(range(len(pods)))
        host = host_ffd.pack(vecs, ids, packables)
        pallas_result = solve_ffd_device(vecs, ids, packables, kernel="pallas",
                                         chunk_iters=8)  # force chunk resume
        xla_result = solve_ffd_device(vecs, ids, packables, kernel="xla")
        assert pallas_result.node_count == host.node_count == xla_result.node_count
        key = lambda r: sorted((tuple(p.instance_type_indices), p.node_quantity)
                               for p in r.packings)
        assert key(pallas_result) == key(host) == key(xla_result)

    def test_unknown_kernel_rejected(self):
        from karpenter_tpu.models.ffd import solve_ffd_device
        from karpenter_tpu.solver.adapter import build_packables, pod_vector

        catalog = instance_types(2)
        pods = [make_pod({"cpu": "100m", "memory": "64Mi"})]
        packables, _ = build_packables(
            catalog, allow_all_constraints(catalog), pods, [])
        with pytest.raises(ValueError, match="unknown device kernel"):
            solve_ffd_device([pod_vector(p) for p in pods], [0], packables,
                             kernel="palas")


class TestPallasRouting:
    """Cardinality routing for the pallas kernel reflects hardware
    measurement (r4): the 8192 bucket is pallas-validated (exact vs the
    per-pod C++ oracle at 5k/8k distinct shapes on TPU), so requests up to
    pallas_max_shapes=8192 keep the pallas kernel; above, the XLA scan."""

    def _spy_problem(self, n_shapes):
        catalog = instance_types(4)
        pods = [make_pod({"cpu": f"{100 + i}m", "memory": "64Mi"})
                for i in range(n_shapes)]
        packables, _ = build_packables(
            catalog, allow_all_constraints(catalog), pods, [])
        vecs = [pod_vector(p) for p in pods]
        return vecs, list(range(len(pods))), packables

    def test_admits_pallas_to_8192(self, monkeypatch):
        import karpenter_tpu.ops.pack_pallas as pp
        from karpenter_tpu.models.ffd import solve_ffd_device
        from karpenter_tpu.ops.pack import pack_chunk_flat

        calls = {"pallas": 0}

        def spy(*args, interpret=False, **kw):
            calls["pallas"] += 1
            kw.pop("prices", None)
            kw.pop("cost_tiebreak", None)
            return pack_chunk_flat(*args, **kw)

        monkeypatch.setattr(pp, "pack_chunk_pallas_flat", spy)
        # 4100 distinct shapes pads to the 8192 bucket — above the OLD cap,
        # within the validated one
        vecs, ids, packables = self._spy_problem(4100)
        result = solve_ffd_device(vecs, ids, packables, kernel="pallas")
        assert result is not None
        assert calls["pallas"] >= 1, (
            "pallas request in the validated 8192 bucket was demoted to xla")

    def test_demotes_above_validated_bucket(self, monkeypatch):
        import karpenter_tpu.ops.pack_pallas as pp
        from karpenter_tpu.models.ffd import solve_ffd_device

        def must_not_run(*a, **kw):
            raise AssertionError("pallas kernel run above its validated cap")

        monkeypatch.setattr(pp, "pack_chunk_pallas_flat", must_not_run)
        vecs, ids, packables = self._spy_problem(600)
        # force a low cap to exercise the demotion branch cheaply
        result = solve_ffd_device(vecs, ids, packables, kernel="pallas",
                                  pallas_max_shapes=512)
        assert result is not None  # solved by the xla kernel instead


class TestFloordivSmall:
    """The kernel's float32 division must be EXACT for every quotient below
    DIV_CAP-2 (ops/pack_pallas.py). Pins the review-r5 counterexample where
    float32 input rounding crossed an integer boundary UPWARD (the original
    correction rounds only adjusted upward, so the kernel over-packed)."""

    def test_upward_rounding_counterexample(self):
        import jax.numpy as jnp

        from karpenter_tpu.ops.pack_pallas import _floordiv_small

        # f32(33558527) = 33558528 → qf = 8193.0 exactly, one above floor
        assert int(_floordiv_small(jnp.int32(33558527),
                                   jnp.int32(4096))) == 8192

    def test_randomized_exactness(self):
        import jax.numpy as jnp

        from karpenter_tpu.ops.pack_pallas import DIV_CAP, _floordiv_small

        rng = np.random.default_rng(7)
        n = 100_000
        bs = rng.integers(1, 2**31 - 1, size=n).astype(np.int64)
        qs = np.minimum(rng.integers(0, DIV_CAP - 2, size=n),
                        (2**31 - 1) // bs)
        rs = (rng.random(n) * bs).astype(np.int64)
        a = qs * bs + rs
        m = a < 2**31
        got = np.asarray(_floordiv_small(jnp.asarray(a[m], jnp.int32),
                                         jnp.asarray(bs[m], jnp.int32)))
        np.testing.assert_array_equal(got, a[m] // bs[m])

    def test_boundary_adversaries(self):
        """a = q·b - 1 and q·b exactly: the fractions nearest an integer
        boundary, where a one-ULP rounding flips the f32 quotient."""
        import jax.numpy as jnp

        from karpenter_tpu.ops.pack_pallas import DIV_CAP, _floordiv_small

        rng = np.random.default_rng(11)
        n = 100_000
        b = rng.integers(1, 2**14, size=n).astype(np.int64)
        q = np.minimum(rng.integers(1, DIV_CAP - 2, size=n),
                       (2**31 - 2) // b)
        for delta in (-1, 0):
            a = q * b + delta
            m = (a >= 0) & (a < 2**31)
            got = np.asarray(_floordiv_small(jnp.asarray(a[m], jnp.int32),
                                             jnp.asarray(b[m], jnp.int32)))
            np.testing.assert_array_equal(got, a[m] // b[m])

    def test_negative_numerator_clips_like_floor(self):
        import jax.numpy as jnp

        from karpenter_tpu.ops.pack_pallas import _floordiv_small

        for a in (-1, -5, -(2**30)):
            assert int(_floordiv_small(jnp.int32(a), jnp.int32(7))) <= 0


class TestPipelinedChunkLoop:
    """The high-cardinality chunk loop (models/ffd.py): device-resident
    counts/dropped carry + speculative next-chunk dispatch + async
    copy-out. On CPU the S*L trigger is never reached naturally, so the
    threshold is forced down to exercise the multi-chunk resume through
    the pipelined path — the result must match the host oracle and the
    unpipelined loop exactly."""

    def _problem(self, n_pods=120):
        catalog = instance_types(6)
        pods = [make_pod({"cpu": f"{100 + 7 * i}m", "memory": "64Mi"})
                for i in range(n_pods)]
        packables, _ = build_packables(
            catalog, allow_all_constraints(catalog), pods, [])
        vecs = [pod_vector(p) for p in pods]
        return vecs, list(range(len(pods))), packables

    @pytest.mark.parametrize("kernel", ["xla", "pallas"])
    def test_pipelined_multi_chunk_resume_exact(self, monkeypatch, kernel):
        import karpenter_tpu.models.ffd as ffd

        vecs, ids, packables = self._problem()
        want = host_ffd.pack(vecs, ids, packables)
        unpipelined = ffd.solve_ffd_device(vecs, ids, packables,
                                           kernel=kernel, chunk_iters=4,
                                           hedge=False)
        monkeypatch.setattr(ffd, "_PIPELINE_ELEMS", 1)  # force the path
        piped = ffd.solve_ffd_device(vecs, ids, packables, kernel=kernel,
                                     chunk_iters=4, hedge=False)
        key = lambda r: (r.node_count, sorted(r.unschedulable),
                         sorted((tuple(p.instance_type_indices),
                                 p.node_quantity) for p in r.packings))
        assert piped is not None and unpipelined is not None
        assert key(piped) == key(want)
        assert key(piped) == key(unpipelined)

    def test_pipelined_single_chunk_exact(self, monkeypatch):
        import karpenter_tpu.models.ffd as ffd

        vecs, ids, packables = self._problem(n_pods=40)
        want = host_ffd.pack(vecs, ids, packables)
        monkeypatch.setattr(ffd, "_PIPELINE_ELEMS", 1)
        got = ffd.solve_ffd_device(vecs, ids, packables, kernel="xla",
                                   hedge=False)
        assert got is not None and got.node_count == want.node_count
