"""Differential tests: TPU pack kernel vs host FFD oracle.

Node count must match EXACTLY (stronger than the ±1 target in BASELINE.md);
pod coverage and instance options must be identical packing-for-packing.
"""

import random

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints
from karpenter_tpu.api.core import (
    Container, NodeSelectorRequirement as Req, Pod, PodSpec, ResourceRequirements,
)
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.fake.provider import instance_types, make_instance_type
from karpenter_tpu.models.ffd import solve_ffd_device, solve_ffd_numpy
from karpenter_tpu.solver import host_ffd
from karpenter_tpu.solver.adapter import build_packables, pod_vector


from karpenter_tpu.controllers.provisioning import universe_constraints


def allow_all_constraints(catalog):
    """Constraints admitting the whole catalog — the production universe
    injection (controller.go:141-162), via the shared helper."""
    return universe_constraints(catalog)


def make_pod(requests, limits=None):
    return Pod(spec=PodSpec(containers=[
        Container(resources=ResourceRequirements.make(requests=requests, limits=limits))]))


def solve_both(pods, catalog, daemons=()):
    constraints = allow_all_constraints(catalog)
    packables, _ = build_packables(catalog, constraints, pods, daemons)
    vecs = [pod_vector(p) for p in pods]
    ids = list(range(len(pods)))
    host = host_ffd.pack(vecs, ids, packables)
    device = solve_ffd_device(vecs, ids, packables)
    assert device is not None, "device path must encode this problem"
    # the numpy kernel-mirror must agree too (it is the 50k-scale oracle)
    numpy_result = solve_ffd_numpy(vecs, ids, packables)
    assert numpy_result is not None
    assert numpy_result.node_count == host.node_count
    assert sorted(numpy_result.unschedulable) == sorted(host.unschedulable)
    return host, device


def assert_parity(host, device, n_pods):
    assert device.node_count == host.node_count
    # identical packing structure: same (options, node_quantity) multiset
    h = sorted((tuple(p.instance_type_indices), p.node_quantity) for p in host.packings)
    d = sorted((tuple(p.instance_type_indices), p.node_quantity) for p in device.packings)
    assert d == h
    # identical unschedulable sets and full pod coverage
    assert sorted(device.unschedulable) == sorted(host.unschedulable)
    covered = sorted(i for p in device.packings for node in p.pod_ids for i in node)
    covered_h = sorted(i for p in host.packings for node in p.pod_ids for i in node)
    assert len(covered) == len(set(covered))
    assert len(covered) + len(device.unschedulable) == n_pods
    assert len(covered_h) + len(host.unschedulable) == n_pods


class TestParitySmoke:
    def test_homogeneous_pods(self):
        pods = [make_pod({"cpu": "1", "memory": "512Mi"}) for _ in range(100)]
        host, device = solve_both(pods, instance_types(10))
        assert_parity(host, device, 100)
        assert host.node_count > 0

    def test_reference_benchmark_fixture(self):
        # packer_test.go:33-74: 10k pods of 1 CPU/512Mi × 100 synthetic types
        pods = [make_pod({"cpu": "1", "memory": "512Mi"}) for _ in range(10_000)]
        host, device = solve_both(pods, instance_types(100))
        assert_parity(host, device, 10_000)

    def test_mixed_sizes(self):
        pods = (
            [make_pod({"cpu": "250m", "memory": "128Mi"}) for _ in range(40)]
            + [make_pod({"cpu": "2", "memory": "4Gi"}) for _ in range(7)]
            + [make_pod({"cpu": "500m", "memory": "1Gi"}) for _ in range(21)]
        )
        host, device = solve_both(pods, instance_types(20))
        assert_parity(host, device, len(pods))

    def test_unschedulable_oversized(self):
        pods = [make_pod({"cpu": "100", "memory": "4Gi"}) for _ in range(3)]
        host, device = solve_both(pods, instance_types(5))
        assert_parity(host, device, 3)
        assert len(device.unschedulable) == 3

    def test_exotic_resource_never_packs(self):
        pods = [make_pod({"cpu": "1", "example.com/widget": "1"}) for _ in range(4)]
        host, device = solve_both(pods, instance_types(5))
        assert_parity(host, device, 4)
        assert len(device.unschedulable) == 4

    def test_gpu_pods_pack_on_gpu_type_only(self):
        catalog = [
            make_instance_type("cpu-type", cpu="8", memory="16Gi", pods="20"),
            make_instance_type("gpu-type", cpu="8", memory="16Gi", pods="20", nvidia_gpus="4"),
        ]
        pods = [make_pod({"cpu": "1", "nvidia.com/gpu": "1"}) for _ in range(8)]
        host, device = solve_both(pods, catalog)
        assert_parity(host, device, 8)
        assert device.node_count == 2  # 4 GPUs per node
        for p in device.packings:
            assert all(i == 0 for i in p.instance_type_indices)  # only gpu-type viable

    def test_daemon_overhead(self):
        daemons = [make_pod({"cpu": "500m", "memory": "256Mi"})]
        pods = [make_pod({"cpu": "1", "memory": "512Mi"}) for _ in range(50)]
        host, device = solve_both(pods, instance_types(10), daemons)
        assert_parity(host, device, 50)

    def test_empty_pods(self):
        host, device = solve_both([], instance_types(5))
        assert device.node_count == 0
        assert host.node_count == 0

    def test_pods_dimension_binds(self):
        # tiny pods: the pods-per-node cap is the binding constraint
        pods = [make_pod({"cpu": "10m", "memory": "8Mi"}) for _ in range(500)]
        host, device = solve_both(pods, instance_types(3))
        assert_parity(host, device, 500)


class TestCompaction:
    """Active-shape compaction at chunk boundaries (ops/compact.py): the
    alive set must actually re-bucket downward mid-solve, and the permuted
    record stream must decode back to the exact host-oracle packing."""

    @staticmethod
    def _distinct_shape_pods(n):
        # every pod a distinct shape: counts hit zero fast, so the alive
        # set shrinks chunk over chunk
        return [make_pod({"cpu": f"{100 + i}m",
                          "memory": f"{64 + (i % 7)}Mi"}) for i in range(n)]

    def test_mid_solve_compaction_exact(self, monkeypatch):
        """chunk_iters=2 forces many chunk boundaries; a spy proves the
        bucket actually shrinks and parity stays exact through the
        permutation decode."""
        from karpenter_tpu.ops import compact as compact_mod

        events = []
        orig = compact_mod.compact_alive

        def spy(counts_now, perm, shapes_full, maxfit_full):
            c = orig(counts_now, perm, shapes_full, maxfit_full)
            if c is not None:
                events.append((counts_now.shape[0], c.num_shapes))
            return c

        monkeypatch.setattr(compact_mod, "compact_alive", spy)
        pods = self._distinct_shape_pods(300)
        catalog = instance_types(10)
        constraints = allow_all_constraints(catalog)
        packables, _ = build_packables(catalog, constraints, pods, ())
        vecs = [pod_vector(p) for p in pods]
        ids = list(range(len(pods)))
        host = host_ffd.pack(vecs, ids, packables)
        device = solve_ffd_device(vecs, ids, packables, chunk_iters=2)
        assert device is not None
        assert events, "compaction never fired on a 512-bucket problem"
        assert all(new < cur for cur, new in events)
        assert_parity(host, device, len(pods))

    def test_compact_off_matches_on(self):
        pods = self._distinct_shape_pods(200)
        catalog = instance_types(8)
        constraints = allow_all_constraints(catalog)
        packables, _ = build_packables(catalog, constraints, pods, ())
        vecs = [pod_vector(p) for p in pods]
        ids = list(range(len(pods)))
        on = solve_ffd_device(vecs, ids, packables, chunk_iters=4)
        off = solve_ffd_device(vecs, ids, packables, chunk_iters=4,
                               compact=False)
        assert on is not None and off is not None
        assert on.node_count == off.node_count
        key = lambda r: sorted(  # noqa: E731
            (tuple(p.instance_type_indices), p.node_quantity,
             tuple(sorted(tuple(sorted(n)) for n in p.pod_ids)))
            for p in r.packings)
        assert key(on) == key(off)
        assert sorted(on.unschedulable) == sorted(off.unschedulable)

    def test_permutation_round_trip(self):
        """compact_alive/sparse_record/scatter_dropped unit round-trip:
        perm always maps compacted rows to ORIGINAL indices, including
        across a second-level compaction (perm composition)."""
        import numpy as np

        from karpenter_tpu.ops.compact import (
            compact_alive, scatter_dropped, sparse_record,
        )

        rng = np.random.default_rng(0)
        S = 64
        counts = np.zeros(S, np.int32)
        alive_idx = np.sort(rng.choice(S, size=9, replace=False))
        counts[alive_idx] = rng.integers(1, 5, size=9).astype(np.int32)
        shapes_full = rng.integers(1, 100, size=(S, 5)).astype(np.int32)
        maxfit_full = rng.integers(0, 9, size=S).astype(np.int32)

        c = compact_alive(counts, None, shapes_full, maxfit_full)
        assert c is not None and c.num_shapes == 16  # 9 alive → bucket 16
        assert np.array_equal(c.perm, alive_idx)  # ascending → order-stable
        assert np.array_equal(c.shapes[:9], shapes_full[alive_idx])
        assert np.array_equal(c.maxfit[:9], maxfit_full[alive_idx])
        assert np.array_equal(c.counts[:9], counts[alive_idx])
        assert not c.shapes[9:].any() and not c.counts[9:].any()

        # sparse records land on ORIGINAL shape indices
        packed = np.zeros(c.num_shapes, np.int32)
        packed[2] = 3
        assert sparse_record(packed, c.perm) == [(int(alive_idx[2]), 3)]

        # dropped deltas scatter into the original accumulator
        full = np.zeros(S, np.int64)
        delta = np.zeros(c.num_shapes, np.int32)
        delta[0] = 2
        scatter_dropped(full, delta, c.perm)
        assert full[alive_idx[0]] == 2 and full.sum() == 2

        # second-level compaction composes permutations
        counts2 = c.counts.copy()
        counts2[[1, 3, 5, 6, 7, 8]] = 0  # 3 alive → bucket 8 < 16
        c2 = compact_alive(counts2, c.perm, shapes_full, maxfit_full)
        assert c2 is not None and c2.num_shapes == 8
        assert np.array_equal(c2.perm, alive_idx[[0, 2, 4]])
        assert np.array_equal(c2.shapes[:3], shapes_full[c2.perm])

        # no-op cases: empty alive set, or bucket cannot shrink (8 is the
        # smallest SHAPE_BUCKET)
        assert compact_alive(np.zeros(S, np.int32), None,
                             shapes_full, maxfit_full) is None
        dense = np.ones(8, np.int32)
        assert compact_alive(dense, None, shapes_full[:8],
                             maxfit_full[:8]) is None
        three = np.zeros(8, np.int32)
        three[:3] = 1
        assert compact_alive(three, None, shapes_full[:8],
                             maxfit_full[:8]) is None


class TestParityFuzz:
    @pytest.mark.parametrize("seed", range(12))
    def test_random_problems(self, seed):
        rng = random.Random(seed)
        n_types = rng.randint(1, 30)
        catalog = instance_types(n_types)
        if rng.random() < 0.4:
            catalog.append(make_instance_type(
                "gpu-extra", cpu="16", memory="32Gi", pods="40", nvidia_gpus="8"))
        pods = []
        n_pods = rng.randint(1, 400)
        kinds = rng.randint(1, 8)
        shapes = []
        for _ in range(kinds):
            shapes.append({
                "cpu": f"{rng.choice([100, 250, 500, 1000, 1500, 2000, 4000, 64000])}m",
                "memory": f"{rng.choice([64, 128, 256, 512, 1024, 3072, 8192])}Mi",
            })
            if rng.random() < 0.2:
                shapes[-1]["nvidia.com/gpu"] = str(rng.randint(1, 2))
        for _ in range(n_pods):
            pods.append(make_pod(dict(rng.choice(shapes))))
        host, device = solve_both(pods, catalog)
        assert_parity(host, device, n_pods)
