"""Coarse performance regression guards.

SURVEY.md §4 notes the reference ships no load/perf regression tests; these
exist to catch order-of-magnitude regressions (an accidentally quadratic
loop, a lost cache) in CI — NOT to measure real performance (bench.py does
that on real hardware). Bounds are ~50-100× looser than measured costs so
slow shared CI runners never flake them.
"""

import time

from karpenter_tpu.api.core import Container, Pod, PodSpec, ResourceRequirements
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.solver.adapter import marshal_pods
from karpenter_tpu.solver.solve import SolverConfig, solve
from karpenter_tpu.utils.fastcopy import deep_copy

MIXED = [(c, m) for c in (100, 500, 1000, 4000) for m in (128, 1024, 4096)]


def mkpods(n):
    return [Pod(spec=PodSpec(containers=[Container(
        resources=ResourceRequirements.make(requests={
            "cpu": f"{c}m", "memory": f"{m}Mi"}))]))
        for i in range(n) for c, m in (MIXED[i % len(MIXED)],)]


class TestPerfSmoke:
    def test_warm_marshal_is_cached_gather(self):
        # cold ≈ 5 ms/1k pods; warm must be an attribute gather. Bound: the
        # warm pass must be at least 3× faster than the cold pass (ratio,
        # not wall clock — immune to slow runners).
        pods = mkpods(20_000)
        t0 = time.perf_counter()
        marshal_pods(pods)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        marshal_pods(pods)
        warm = time.perf_counter() - t0
        assert warm < cold / 3, (
            f"marshal cache ineffective: cold={cold * 1e3:.0f}ms "
            f"warm={warm * 1e3:.0f}ms")

    @staticmethod
    def _timed_warm_solve(n_pods):
        """Shared warm-solve protocol: fake catalog, host executors
        (CI-stable), one warm-up pass, one timed pass."""
        catalog = instance_types(40)
        constraints = universe_constraints(catalog)
        pods = mkpods(n_pods)
        config = SolverConfig(use_device=False)
        solve(constraints, pods, catalog, config=config)  # warm caches
        t0 = time.perf_counter()
        result = solve(constraints, pods, catalog, config=config)
        elapsed = time.perf_counter() - t0
        return result, elapsed, (catalog, constraints, pods)

    def test_warm_solve_50k_under_loose_bound(self):
        result, elapsed, _ = self._timed_warm_solve(50_000)
        assert result.node_count > 0
        # measured ~60 ms; 5 s catches accidental O(pods²) / lost caches
        assert elapsed < 5.0, f"50k-pod warm solve took {elapsed:.1f}s"

    def test_100k_pods_exact_and_bounded(self):
        """The reference caps batches at 2,000 pods for memory (SURVEY
        §5.7); this framework claims the cap is gone. Evidence at 2× the
        headline scale: 100k pods solve exactly (vs the per-pod oracle's
        node count via the numpy mirror) inside a loose wall bound."""
        from karpenter_tpu.models.ffd import solve_ffd_numpy
        from karpenter_tpu.solver.adapter import build_packables, pod_vectors

        result, elapsed, (catalog, constraints, pods) = (
            self._timed_warm_solve(100_000))
        packables, _ = build_packables(catalog, constraints, pods, [])
        mirror = solve_ffd_numpy(pod_vectors(pods),
                                 list(range(len(pods))), packables)
        assert result.node_count == mirror.node_count
        assert not result.unschedulable
        assert elapsed < 10.0, f"100k-pod warm solve took {elapsed:.1f}s"

    def test_fastcopy_beats_stdlib(self):
        import copy

        pod = mkpods(1)[0]
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            copy.deepcopy(pod)
        std = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            deep_copy(pod)
        fast = time.perf_counter() - t0
        assert fast < std, (
            f"fastcopy regressed below copy.deepcopy: {fast:.3f}s vs {std:.3f}s")


class TestGcGuard:
    def test_defers_and_restores(self):
        import gc

        from karpenter_tpu.utils.gcguard import gc_deferred

        assert gc.isenabled()
        with gc_deferred():
            assert not gc.isenabled()
            with gc_deferred():  # reentrant
                assert not gc.isenabled()
            assert not gc.isenabled()  # inner exit must not re-enable
        assert gc.isenabled()

    def test_respects_externally_disabled_gc(self):
        import gc

        from karpenter_tpu.utils.gcguard import gc_deferred

        gc.disable()
        try:
            with gc_deferred():
                assert not gc.isenabled()
            assert not gc.isenabled()  # the guard didn't own the disable
        finally:
            gc.enable()

    def test_solve_path_runs_under_guard(self):
        """solve() must not leave GC disabled after returning."""
        import gc

        from karpenter_tpu.cloudprovider.fake.provider import instance_types
        from karpenter_tpu.controllers.provisioning import universe_constraints
        from karpenter_tpu.solver.solve import solve
        from tests.expectations import unschedulable_pod

        catalog = instance_types(6)
        constraints = universe_constraints(catalog)
        pods = [unschedulable_pod(requests={"cpu": "500m"}) for _ in range(20)]
        solve(constraints, pods, catalog)
        assert gc.isenabled()
