"""Coarse performance regression guards.

SURVEY.md §4 notes the reference ships no load/perf regression tests; these
exist to catch order-of-magnitude regressions (an accidentally quadratic
loop, a lost cache) in CI — NOT to measure real performance (bench.py does
that on real hardware). Bounds are ~50-100× looser than measured costs so
slow shared CI runners never flake them.
"""

import time

from karpenter_tpu.api.core import Container, Pod, PodSpec, ResourceRequirements
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.solver.adapter import marshal_pods
from karpenter_tpu.solver.solve import SolverConfig, solve
from karpenter_tpu.utils.fastcopy import deep_copy

MIXED = [(c, m) for c in (100, 500, 1000, 4000) for m in (128, 1024, 4096)]


def mkpods(n):
    return [Pod(spec=PodSpec(containers=[Container(
        resources=ResourceRequirements.make(requests={
            "cpu": f"{c}m", "memory": f"{m}Mi"}))]))
        for i in range(n) for c, m in (MIXED[i % len(MIXED)],)]


class TestPerfSmoke:
    def test_warm_marshal_is_cached_gather(self):
        # cold ≈ 5 ms/1k pods; warm must be an attribute gather. Bound: the
        # warm pass must be at least 3× faster than the cold pass (ratio,
        # not wall clock — immune to slow runners).
        pods = mkpods(20_000)
        t0 = time.perf_counter()
        marshal_pods(pods)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        marshal_pods(pods)
        warm = time.perf_counter() - t0
        assert warm < cold / 3, (
            f"marshal cache ineffective: cold={cold * 1e3:.0f}ms "
            f"warm={warm * 1e3:.0f}ms")

    @staticmethod
    def _timed_warm_solve(n_pods):
        """Shared warm-solve protocol: fake catalog, host executors
        (CI-stable), one warm-up pass, one timed pass."""
        catalog = instance_types(40)
        constraints = universe_constraints(catalog)
        pods = mkpods(n_pods)
        config = SolverConfig(use_device=False)
        solve(constraints, pods, catalog, config=config)  # warm caches
        t0 = time.perf_counter()
        result = solve(constraints, pods, catalog, config=config)
        elapsed = time.perf_counter() - t0
        return result, elapsed, (catalog, constraints, pods)

    def test_warm_solve_50k_under_loose_bound(self):
        result, elapsed, _ = self._timed_warm_solve(50_000)
        assert result.node_count > 0
        # measured ~60 ms; 5 s catches accidental O(pods²) / lost caches
        from tests.expectations import host_loaded

        if not host_loaded("50k warm-solve wall bound"):
            assert elapsed < 5.0, f"50k-pod warm solve took {elapsed:.1f}s"

    def test_100k_pods_exact_and_bounded(self):
        """The reference caps batches at 2,000 pods for memory (SURVEY
        §5.7); this framework claims the cap is gone. Evidence at 2× the
        headline scale: 100k pods solve exactly (vs the per-pod oracle's
        node count via the numpy mirror) inside a loose wall bound."""
        from karpenter_tpu.models.ffd import solve_ffd_numpy
        from karpenter_tpu.solver.adapter import build_packables, pod_vectors

        result, elapsed, (catalog, constraints, pods) = (
            self._timed_warm_solve(100_000))
        packables, _ = build_packables(catalog, constraints, pods, [])
        mirror = solve_ffd_numpy(pod_vectors(pods),
                                 list(range(len(pods))), packables)
        assert result.node_count == mirror.node_count
        assert not result.unschedulable
        from tests.expectations import host_loaded

        if not host_loaded("100k warm-solve wall bound"):
            assert elapsed < 10.0, f"100k-pod warm solve took {elapsed:.1f}s"

    def test_fastcopy_beats_stdlib(self):
        import copy

        pod = mkpods(1)[0]
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            copy.deepcopy(pod)
        std = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            deep_copy(pod)
        fast = time.perf_counter() - t0
        assert fast < std, (
            f"fastcopy regressed below copy.deepcopy: {fast:.3f}s vs {std:.3f}s")


class TestExecutorRouting:
    """Pin the executor ROUTING decisions (VERDICT r4 #8): a silent
    demotion — the exact bug that kept the pallas kernel out of the
    cost-minimizing production path for a round — must fail CI, not wait
    for a human to read a capture."""

    def _problem(self, n_pods=600, n_types=16):
        catalog = instance_types(n_types)
        for i, it in enumerate(catalog):
            it.price = 0.1 * (len(catalog) - i)
        constraints = universe_constraints(catalog)
        return catalog, constraints, mkpods(n_pods)

    def test_pallas_serves_cost_mode(self, monkeypatch):
        """kernel='pallas' + cost_tiebreak must run the PALLAS kernel."""
        import karpenter_tpu.ops.pack_pallas as pp

        calls = {"n": 0}
        real = pp.pack_chunk_pallas_flat

        def spy(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(pp, "pack_chunk_pallas_flat", spy)
        catalog, constraints, pods = self._problem()
        res = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_kernel="pallas", cost_tiebreak=True))
        assert res.node_count > 0
        assert calls["n"] >= 1, (
            "pallas request in cost mode was demoted to another executor")

    def test_type_spmd_serves_cost_mode(self, monkeypatch):
        import karpenter_tpu.parallel.type_sharded as ts

        calls = {"n": 0}
        real = ts.pack_chunk_type_sharded

        def spy(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(ts, "pack_chunk_type_sharded", spy)
        catalog, constraints, pods = self._problem()
        res = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_kernel="type-spmd",
            cost_tiebreak=True))
        assert res.node_count > 0
        assert calls["n"] >= 1, (
            "type-spmd request in cost mode was demoted to another executor")

    def test_batched_pallas_serves_cost_mode(self, monkeypatch):
        import karpenter_tpu.ops.pack_pallas as pp
        from karpenter_tpu.solver.batch_solve import Problem, solve_batch

        calls = {"n": 0}
        real = pp.pack_chunk_pallas_flat

        def spy(*a, **kw):
            calls["n"] += 1
            return real(*a, **kw)

        monkeypatch.setattr(pp, "pack_chunk_pallas_flat", spy)
        # the batched entry jit-traces the per-problem kernel INTO its
        # cache; an earlier test's trace with the same static signature
        # would bypass the spy — clear it so the routing is re-traced
        from karpenter_tpu.parallel.sharded_pack import pack_batch_sharded_flat

        pack_batch_sharded_flat.clear_cache()
        catalog, constraints, pods = self._problem(n_pods=300)
        problems = [Problem(constraints=constraints, pods=pods[:150],
                            instance_types=catalog),
                    Problem(constraints=constraints, pods=pods[150:],
                            instance_types=catalog)]
        solve_batch(problems, config=SolverConfig(
            device_min_pods=1, device_kernel="pallas", cost_tiebreak=True))
        assert calls["n"] >= 1, (
            "batched pallas request in cost mode was demoted")

    def test_high_cardinality_routes_native(self):
        """Above device_max_shapes the production path must answer via the
        per-pod native ring, not trudge through the device."""
        from karpenter_tpu.solver import solve as solve_module

        catalog = instance_types(6)
        constraints = universe_constraints(catalog)
        pods = [Pod(spec=PodSpec(containers=[Container(
            resources=ResourceRequirements.make(requests={
                "cpu": f"{100 + i}m", "memory": "64Mi"}))]))
            for i in range(1200)]
        res = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_max_shapes=1024))
        assert res.node_count > 0
        assert solve_module.solver_health()["last_executor"] == "native"


class TestHardwareEnvelope:
    """Per-config envelopes pinned to the most recent hardware capture for
    each config — run on the real backend only (KARPENTER_HW_ENVELOPE=1;
    CI forces CPU where the numbers are meaningless). Failing this before
    a capture means a perf regression shipped since that capture."""

    def test_headline_p50_within_2x_of_r4_capture(self):
        import json
        import os

        import pytest

        if os.environ.get("KARPENTER_HW_ENVELOPE") != "1":
            pytest.skip("hardware envelope runs only with "
                        "KARPENTER_HW_ENVELOPE=1 on the real backend")
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("needs the real TPU backend")
        import bench

        # BENCH_r04_final.json is the round-4 final-tree capture (the
        # driver's own BENCH_r04.json truncates its output tail, so the
        # builder capture is the parseable record of the same tree)
        with open(os.path.join(os.path.dirname(bench.__file__),
                               "BENCH_r04_final.json")) as f:
            r4 = json.load(f)
        r4_p50 = r4["extra"]["config_4_50k_pods_cost_minimizing"]["p50_ms"]
        times, _ = bench.config_4_headline()
        p50 = bench._stats(times)["p50_ms"]
        assert p50 < 2 * r4_p50, (
            f"headline p50 {p50:.1f} ms exceeds 2x the r4 capture "
            f"({r4_p50:.1f} ms)")

    def test_8192_bucket_p50_within_2x_of_r5_capture(self):
        """The rewritten pallas kernel's 8192-shape performance (1.9 s p50,
        BENCH_r05_builder.json config 6a) must not silently regress toward
        its 9.5 s past."""
        import json
        import os

        import pytest

        if os.environ.get("KARPENTER_HW_ENVELOPE") != "1":
            pytest.skip("hardware envelope runs only with "
                        "KARPENTER_HW_ENVELOPE=1 on the real backend")
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("needs the real TPU backend")
        import bench

        with open(os.path.join(os.path.dirname(bench.__file__),
                               "BENCH_r05_builder.json")) as f:
            r5 = json.load(f)
        cfg = r5["extra"]["config_6_high_shape_cardinality"]
        r5_p50 = cfg["device_8k_shapes"]["p50_ms"]
        r5_auto_p50 = cfg["auto_25k_shapes"]["p50_ms"]
        out = bench.config_6_high_cardinality()
        assert "error" not in out["device_8k_shapes"], (
            f"device path declined the 8k-shape problem — routing "
            f"regression: {out['device_8k_shapes']}")
        p50 = out["device_8k_shapes"]["p50_ms"]
        assert p50 < 2 * r5_p50, (
            f"8192-bucket p50 {p50:.0f} ms exceeds 2x the r5 capture "
            f"({r5_p50:.0f} ms) — kernel regression")
        # the 25k-shape half runs anyway inside config_6 — envelope it too
        # (per-pod C++ auto-route, r5 capture 325.9 ms)
        auto_p50 = out["auto_25k_shapes"]["p50_ms"]
        assert auto_p50 < 2 * r5_auto_p50, (
            f"25k-shape auto-routed p50 {auto_p50:.0f} ms exceeds 2x the "
            f"r5 capture ({r5_auto_p50:.0f} ms)")


class TestGcGuard:
    def test_defers_and_restores(self):
        import gc

        from karpenter_tpu.utils.gcguard import gc_deferred

        assert gc.isenabled()
        with gc_deferred():
            assert not gc.isenabled()
            with gc_deferred():  # reentrant
                assert not gc.isenabled()
            assert not gc.isenabled()  # inner exit must not re-enable
        assert gc.isenabled()

    def test_respects_externally_disabled_gc(self):
        import gc

        from karpenter_tpu.utils.gcguard import gc_deferred

        gc.disable()
        try:
            with gc_deferred():
                assert not gc.isenabled()
            assert not gc.isenabled()  # the guard didn't own the disable
        finally:
            gc.enable()

    def test_solve_path_runs_under_guard(self):
        """solve() must not leave GC disabled after returning."""
        import gc

        from karpenter_tpu.cloudprovider.fake.provider import instance_types
        from karpenter_tpu.controllers.provisioning import universe_constraints
        from karpenter_tpu.solver.solve import solve
        from tests.expectations import unschedulable_pod

        catalog = instance_types(6)
        constraints = universe_constraints(catalog)
        pods = [unschedulable_pod(requests={"cpu": "500m"}) for _ in range(20)]
        solve(constraints, pods, catalog)
        assert gc.isenabled()
