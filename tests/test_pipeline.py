"""Pipelined hot loop (solver/pipeline.py + the provisioning worker).

The pipeline buys overlap, never answers: a depth-2 run must be
result-identical — per-problem node sets AND bind order — to the serial
path, across seeds, including when a mid-pipeline device fault trips the
watchdog and the outstanding chunks fall back to the host executors. The
executor itself must collapse to serial at pressure L1+ and drain every
dispatched handle on failure (no SolveResult dropped, none double-fetched).
"""

import random

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.chaos import inject
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import (
    ProvisionerWorker, universe_constraints,
)
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.solver import solve as solve_mod
from karpenter_tpu.solver.pipeline import PipelineConfig, SolvePipeline
from karpenter_tpu.solver.solve import SolverConfig
from karpenter_tpu.runtime.kubecore import KubeCore
from tests.expectations import make_provisioner, unschedulable_pod


@pytest.fixture()
def fresh_watchdog(monkeypatch):
    wd = solve_mod._DeviceWatchdog()
    monkeypatch.setattr(solve_mod, "_WATCHDOG", wd)
    return wd


def make_pods(seed: int, n: int = 120):
    """Deterministic pod population: a few request shapes (so the device
    batch stays in one compile bucket) and alternating zone selectors (so
    each chunk schedules into >= 2 problems and actually batches)."""
    rng = random.Random(seed)
    cpus = ["250m", "500m", "1"]
    mems = ["256Mi", "512Mi"]
    pods = []
    for i in range(n):
        selector = {}
        if i % 2:
            selector = {wellknown.LABEL_TOPOLOGY_ZONE:
                        rng.choice(["test-zone-1", "test-zone-2"])}
        pods.append(unschedulable_pod(
            requests={"cpu": rng.choice(cpus), "memory": rng.choice(mems)},
            node_selector=selector, name=f"pod-s{seed}-{i:03d}"))
    return pods


def run_provision(seed: int, depth: int, n: int = 120, chunk_items: int = 25):
    """One full worker pass at the given pipeline depth; returns the bind
    groups (tuples of pod names) in bind-call order plus the node count."""
    kube = KubeCore()
    catalog = instance_types(6)
    provider = FakeCloudProvider(catalog=catalog)
    provisioner = make_provisioner(constraints=universe_constraints(catalog))
    kube.create(provisioner)
    worker = ProvisionerWorker(
        provisioner, kube, provider,
        solver_config=SolverConfig(device_min_pods=1),
        batcher=Batcher(idle_seconds=0.05, max_seconds=5.0),
        pipeline_config=PipelineConfig(depth=depth, chunk_items=chunk_items))
    binds = []
    orig_bind = worker._bind

    def recording_bind(node, pods):
        binds.append(tuple(sorted(p.metadata.name for p in pods)))
        return orig_bind(node, pods)

    worker._bind = recording_bind
    pods = make_pods(seed, n)
    for pod in pods:
        kube.create(pod)
        gate = worker.add(pod, key=(pod.metadata.namespace, pod.metadata.name))
        assert gate is not None, "L0 admission shed a pod"
    worker.provision()
    worker.stop()
    return binds, len(kube.list("Node")), [p.metadata.name for p in pods]


class TestDifferentialPipelinedVsSerial:
    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_depth2_identical_to_serial(self, seed, fresh_watchdog):
        serial_binds, serial_nodes, pod_names = run_provision(seed, depth=1)
        piped_binds, piped_nodes, _ = run_provision(seed, depth=2)
        # every pod bound exactly once in both modes
        flat = sorted(name for group in piped_binds for name in group)
        assert flat == sorted(pod_names)
        # node parity AND bind order: the pipeline must not reorder chunks
        assert piped_nodes == serial_nodes
        assert piped_binds == serial_binds

    def test_chaos_midpipeline_watchdog_trip_loses_nothing(
            self, fresh_watchdog):
        """A device fault on the FIRST fetch (while the next chunk is
        already dispatched behind it) must fall back to the host executors
        without losing or duplicating a single pod — and the degraded run
        still matches the serial answer node-for-node."""
        seed = 7
        serial_binds, serial_nodes, pod_names = run_provision(seed, depth=1)
        plan = inject.FaultPlan(11, [
            inject.FaultSpec("device", "solve", "watchdog-trip", 1)],
            window=1)
        inject.install(plan)
        try:
            chaos_binds, chaos_nodes, _ = run_provision(seed, depth=2)
        finally:
            inject.uninstall()
        assert plan.fired_counts() == {
            ("device", "solve", "watchdog-trip"): 1}
        # chunk 0's fetch tripped the breaker (the log shows the 120 s open),
        # but chunk 1 — dispatched BEFORE the trip and healthy — closes it
        # again when its own fetch succeeds: the pipeline recovers to the
        # device path within the same window instead of staying degraded
        assert not solve_mod._WATCHDOG.tripped(), (
            "healthy in-flight chunk did not close the breaker")
        # no pod lost, none bound twice
        flat = sorted(name for group in chaos_binds for name in group)
        assert flat == sorted(pod_names)
        # fallback answers are differential with the device path, so even
        # the degraded run matches the serial baseline exactly
        assert chaos_nodes == serial_nodes
        assert chaos_binds == serial_binds


class _CountingHandle:
    def __init__(self, results, tracker):
        self._results = results
        self._tracker = tracker
        self.fetches = 0

    def fetch(self):
        self.fetches += 1
        self._tracker["now"] -= 1
        return self._results


class _Monitor:
    def __init__(self, level):
        self._level = level

    def level(self):
        return self._level


class TestPressureCollapse:
    def test_effective_depth_collapses_at_l1(self):
        pipe = SolvePipeline(PipelineConfig(depth=3), monitor=_Monitor(1))
        assert pipe.effective_depth() == 1
        pipe = SolvePipeline(PipelineConfig(depth=3), monitor=_Monitor(0))
        assert pipe.effective_depth() == 3
        # depth 1 stays serial regardless of the ladder
        pipe = SolvePipeline(PipelineConfig(depth=1), monitor=_Monitor(0))
        assert pipe.effective_depth() == 1

    @pytest.mark.parametrize("level,want_max", [(0, 2), (1, 1), (2, 1)])
    def test_run_bounds_inflight_handles(self, level, want_max):
        tracker = {"now": 0, "max": 0}
        handles = []

        def dispatch(prep):
            tracker["now"] += 1
            tracker["max"] = max(tracker["max"], tracker["now"])
            handle = _CountingHandle([prep], tracker)
            handles.append(handle)
            return handle

        pipe = SolvePipeline(PipelineConfig(depth=2, chunk_items=0),
                             monitor=_Monitor(level))
        outs = pipe.run(list(range(6)), prepare=lambda c: c,
                        dispatch=dispatch,
                        consume=lambda prep, results: results[0])
        assert outs == list(range(6))
        assert tracker["max"] == want_max
        # FIFO pop: every dispatched handle fetched exactly once
        assert [h.fetches for h in handles] == [1] * 6


class TestDrain:
    def test_consume_failure_drains_every_dispatched_handle(self):
        tracker = {"now": 0, "max": 0}
        handles = []
        consumed = []

        def dispatch(prep):
            tracker["now"] += 1
            handle = _CountingHandle([prep], tracker)
            handles.append(handle)
            return handle

        def consume(prep, results):
            consumed.append(prep)
            raise ValueError("bind exploded")

        pipe = SolvePipeline(PipelineConfig(depth=2, chunk_items=0))
        with pytest.raises(ValueError):
            pipe.run(list(range(4)), prepare=lambda c: c,
                     dispatch=dispatch, consume=consume)
        # chunks 0 and 1 were dispatched before the first consume raised;
        # BOTH must still be fetched (and consumption attempted) exactly
        # once — nothing dropped, nothing double-launched
        assert len(handles) == 2
        assert [h.fetches for h in handles] == [1, 1]
        assert consumed == [0, 1]

    def test_fetch_failure_drains_remaining_handles(self):
        handles = []

        class _Exploding:
            def __init__(self, boom):
                self.boom = boom
                self.fetches = 0

            def fetch(self):
                self.fetches += 1
                if self.boom:
                    raise RuntimeError("transport died")
                return ["ok"]

        def dispatch(prep):
            handle = _Exploding(boom=(prep == 0))
            handles.append(handle)
            return handle

        pipe = SolvePipeline(PipelineConfig(depth=2, chunk_items=0))
        with pytest.raises(RuntimeError):
            pipe.run(list(range(4)), prepare=lambda c: c,
                     dispatch=dispatch,
                     consume=lambda prep, results: results[0])
        assert [h.fetches for h in handles] == [1, 1]
