"""Pipelined hot loop (solver/pipeline.py + the provisioning worker).

The pipeline buys overlap, never answers: a depth-2 run must be
result-identical — per-problem node sets AND bind order — to the serial
path, across seeds, including when a mid-pipeline device fault trips the
watchdog and the outstanding chunks fall back to the host executors. The
executor itself must collapse to serial at pressure L1+ and drain every
dispatched handle on failure (no SolveResult dropped, none double-fetched).
"""

import random

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.chaos import inject
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import (
    ProvisionerWorker, universe_constraints,
)
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.solver import solve as solve_mod
from karpenter_tpu.solver.pipeline import PipelineConfig, SolvePipeline
from karpenter_tpu.solver.solve import SolverConfig
from karpenter_tpu.runtime.kubecore import KubeCore
from tests.expectations import make_provisioner, unschedulable_pod


@pytest.fixture()
def fresh_watchdog(monkeypatch):
    wd = solve_mod._DeviceWatchdog()
    monkeypatch.setattr(solve_mod, "_WATCHDOG", wd)
    return wd


def make_pods(seed: int, n: int = 120):
    """Deterministic pod population: a few request shapes (so the device
    batch stays in one compile bucket) and alternating zone selectors (so
    each chunk schedules into >= 2 problems and actually batches)."""
    rng = random.Random(seed)
    cpus = ["250m", "500m", "1"]
    mems = ["256Mi", "512Mi"]
    pods = []
    for i in range(n):
        selector = {}
        if i % 2:
            selector = {wellknown.LABEL_TOPOLOGY_ZONE:
                        rng.choice(["test-zone-1", "test-zone-2"])}
        pods.append(unschedulable_pod(
            requests={"cpu": rng.choice(cpus), "memory": rng.choice(mems)},
            node_selector=selector, name=f"pod-s{seed}-{i:03d}"))
    return pods


def run_provision(seed: int, depth: int, n: int = 120, chunk_items: int = 25,
                  donate: bool = True):
    """One full worker pass at the given pipeline depth; returns the bind
    groups (tuples of pod names) in bind-call order plus the node count."""
    kube = KubeCore()
    catalog = instance_types(6)
    provider = FakeCloudProvider(catalog=catalog)
    provisioner = make_provisioner(constraints=universe_constraints(catalog))
    kube.create(provisioner)
    worker = ProvisionerWorker(
        provisioner, kube, provider,
        solver_config=SolverConfig(device_min_pods=1, device_donate=donate),
        batcher=Batcher(idle_seconds=0.05, max_seconds=5.0),
        pipeline_config=PipelineConfig(depth=depth, chunk_items=chunk_items,
                                       adaptive=False))
    binds = []
    orig_bind = worker._bind

    def recording_bind(node, pods):
        binds.append(tuple(sorted(p.metadata.name for p in pods)))
        return orig_bind(node, pods)

    worker._bind = recording_bind
    pods = make_pods(seed, n)
    for pod in pods:
        kube.create(pod)
        gate = worker.add(pod, key=(pod.metadata.namespace, pod.metadata.name))
        assert gate is not None, "L0 admission shed a pod"
    worker.provision()
    worker.stop()
    return binds, len(kube.list("Node")), [p.metadata.name for p in pods]


class TestDifferentialPipelinedVsSerial:
    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_depth2_identical_to_serial(self, seed, fresh_watchdog):
        serial_binds, serial_nodes, pod_names = run_provision(seed, depth=1)
        piped_binds, piped_nodes, _ = run_provision(seed, depth=2)
        # every pod bound exactly once in both modes
        flat = sorted(name for group in piped_binds for name in group)
        assert flat == sorted(pod_names)
        # node parity AND bind order: the pipeline must not reorder chunks
        assert piped_nodes == serial_nodes
        assert piped_binds == serial_binds

    def test_chaos_midpipeline_watchdog_trip_loses_nothing(
            self, fresh_watchdog):
        """A device fault on the FIRST fetch (while the next chunk is
        already dispatched behind it) must fall back to the host executors
        without losing or duplicating a single pod — and the degraded run
        still matches the serial answer node-for-node."""
        seed = 7
        serial_binds, serial_nodes, pod_names = run_provision(seed, depth=1)
        plan = inject.FaultPlan(11, [
            inject.FaultSpec("device", "solve", "watchdog-trip", 1)],
            window=1)
        inject.install(plan)
        try:
            chaos_binds, chaos_nodes, _ = run_provision(seed, depth=2)
        finally:
            inject.uninstall()
        assert plan.fired_counts() == {
            ("device", "solve", "watchdog-trip"): 1}
        # chunk 0's fetch tripped the breaker (the log shows the 120 s open),
        # but chunk 1 — dispatched BEFORE the trip and healthy — closes it
        # again when its own fetch succeeds: the pipeline recovers to the
        # device path within the same window instead of staying degraded
        assert not solve_mod._WATCHDOG.tripped(), (
            "healthy in-flight chunk did not close the breaker")
        # no pod lost, none bound twice
        flat = sorted(name for group in chaos_binds for name in group)
        assert flat == sorted(pod_names)
        # fallback answers are differential with the device path, so even
        # the degraded run matches the serial baseline exactly
        assert chaos_nodes == serial_nodes
        assert chaos_binds == serial_binds


class _CountingHandle:
    def __init__(self, results, tracker):
        self._results = results
        self._tracker = tracker
        self.fetches = 0

    def fetch(self):
        self.fetches += 1
        self._tracker["now"] -= 1
        return self._results


class _Monitor:
    def __init__(self, level):
        self._level = level

    def level(self):
        return self._level


class TestPressureCollapse:
    def test_effective_depth_collapses_at_l1(self):
        pipe = SolvePipeline(PipelineConfig(depth=3), monitor=_Monitor(1))
        assert pipe.effective_depth() == 1
        pipe = SolvePipeline(PipelineConfig(depth=3), monitor=_Monitor(0))
        assert pipe.effective_depth() == 3
        # depth 1 stays serial regardless of the ladder
        pipe = SolvePipeline(PipelineConfig(depth=1), monitor=_Monitor(0))
        assert pipe.effective_depth() == 1

    @pytest.mark.parametrize("level,want_max", [(0, 2), (1, 1), (2, 1)])
    def test_run_bounds_inflight_handles(self, level, want_max):
        tracker = {"now": 0, "max": 0}
        handles = []

        def dispatch(prep):
            tracker["now"] += 1
            tracker["max"] = max(tracker["max"], tracker["now"])
            handle = _CountingHandle([prep], tracker)
            handles.append(handle)
            return handle

        pipe = SolvePipeline(PipelineConfig(depth=2, chunk_items=0),
                             monitor=_Monitor(level))
        outs = pipe.run(list(range(6)), prepare=lambda c: c,
                        dispatch=dispatch,
                        consume=lambda prep, results: results[0])
        assert outs == list(range(6))
        assert tracker["max"] == want_max
        # FIFO pop: every dispatched handle fetched exactly once
        assert [h.fetches for h in handles] == [1] * 6


class TestDrain:
    def test_consume_failure_drains_every_dispatched_handle(self):
        tracker = {"now": 0, "max": 0}
        handles = []
        consumed = []

        def dispatch(prep):
            tracker["now"] += 1
            handle = _CountingHandle([prep], tracker)
            handles.append(handle)
            return handle

        def consume(prep, results):
            consumed.append(prep)
            raise ValueError("bind exploded")

        pipe = SolvePipeline(PipelineConfig(depth=2, chunk_items=0))
        with pytest.raises(ValueError):
            pipe.run(list(range(4)), prepare=lambda c: c,
                     dispatch=dispatch, consume=consume)
        # chunks 0 and 1 were dispatched before the first consume raised;
        # BOTH must still be fetched (and consumption attempted) exactly
        # once — nothing dropped, nothing double-launched
        assert len(handles) == 2
        assert [h.fetches for h in handles] == [1, 1]
        assert consumed == [0, 1]

    def test_fetch_failure_drains_remaining_handles(self):
        handles = []

        class _Exploding:
            def __init__(self, boom):
                self.boom = boom
                self.fetches = 0

            def fetch(self):
                self.fetches += 1
                if self.boom:
                    raise RuntimeError("transport died")
                return ["ok"]

        def dispatch(prep):
            handle = _Exploding(boom=(prep == 0))
            handles.append(handle)
            return handle

        pipe = SolvePipeline(PipelineConfig(depth=2, chunk_items=0))
        with pytest.raises(RuntimeError):
            pipe.run(list(range(4)), prepare=lambda c: c,
                     dispatch=dispatch,
                     consume=lambda prep, results: results[0])
        assert [h.fetches for h in handles] == [1, 1]


def _tiny_batch(mesh):
    """Smallest-bucket batch args in the sharded flat ABI, one problem
    replicated across the mesh's batch rows."""
    import numpy as np

    from karpenter_tpu.solver.host_ffd import NUM_RESOURCES

    B, S, T = mesh.devices.size, 8, 8
    shapes = np.zeros((B, S, NUM_RESOURCES), np.int32)
    shapes[:, 0, :] = 1
    counts = np.zeros((B, S), np.int32)
    counts[:, 0] = 3
    totals = np.zeros((B, T, NUM_RESOURCES), np.int32)
    totals[:, 0, :] = 64
    valid = np.zeros((B, T), bool)
    valid[:, 0] = True
    return dict(
        shapes=shapes, counts=counts, dropped=np.zeros((B, S), np.int32),
        totals=totals, reserved0=np.zeros((B, T, NUM_RESOURCES), np.int32),
        valid=valid, last_valid=np.zeros((B,), np.int32),
        pods_unit=np.ones((B,), np.int32))


class TestDonatedRing:
    """The donation acceptance surface: the ring buys memory, never answers
    (donated == non-donated bit-for-bit), steady state allocates nothing,
    and a consumed buffer fails loudly — never returns garbage."""

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_ring_identical_to_nondonated(self, seed, fresh_watchdog):
        plain_binds, plain_nodes, pod_names = run_provision(
            seed, depth=2, donate=False)
        ring_binds, ring_nodes, _ = run_provision(seed, depth=2, donate=True)
        flat = sorted(name for group in ring_binds for name in group)
        assert flat == sorted(pod_names)
        assert ring_nodes == plain_nodes
        assert ring_binds == plain_binds

    def test_steady_state_windows_allocate_zero(self, fresh_watchdog):
        """Window 1 populates the ring (counted allocations); an identical
        window 2 must be ALL in-place refills — the round-8 zero-fresh-
        device-allocation gate, asserted on the ring's own ledger."""
        from karpenter_tpu.solver import pipeline as pl

        pl.reset_ring()
        run_provision(1, depth=2, donate=True)
        c1 = pl.get_ring().counters()
        assert c1["allocations"] > 0 and c1["slots"] >= 1
        run_provision(1, depth=2, donate=True)
        c2 = pl.get_ring().counters()
        assert c2["allocations"] == c1["allocations"], (
            f"steady-state window allocated fresh device buffers: {c2}")
        assert c2["refills"] > c1["refills"]

    def test_refill_aliases_same_device_memory(self):
        """The refill path really is in place: the refilled array owns the
        SAME device buffer (pointer-equal), with the new bytes."""
        import numpy as np

        import jax

        from karpenter_tpu.parallel.mesh import batch_sharding, solver_mesh
        from karpenter_tpu.solver.pipeline import DeviceRing

        mesh = solver_mesh()
        bs = batch_sharding(mesh)
        ring = DeviceRing()
        host = np.arange(2 * mesh.devices.size, dtype=np.int32).reshape(
            mesh.devices.size, 2)
        sig = DeviceRing.signature({"counts": host})
        slot = ring.acquire(sig)
        first = ring.fill(slot, "counts", host, bs)
        ptr0 = first.addressable_shards[0].data.unsafe_buffer_pointer()
        second = ring.fill(slot, "counts", host + 5, bs)
        jax.block_until_ready(second)
        assert second.addressable_shards[0].data.unsafe_buffer_pointer() == ptr0
        assert np.array_equal(np.asarray(second), host + 5)
        assert ring.counters() == {
            "allocations": 1, "refills": 1, "reuses": 0, "slots": 1}

    def test_donated_buffer_read_raises_cleanly(self):
        """Use-after-donate guard: the kernel CONSUMES counts/dropped; any
        later read of the donated array must raise RuntimeError (jax deletes
        the buffer), never return stale or garbage bytes."""
        import numpy as np

        import jax

        from karpenter_tpu.parallel.mesh import batch_sharding, solver_mesh
        from karpenter_tpu.parallel.sharded_pack import pack_batch_sharded_ring

        mesh = solver_mesh()
        bs = batch_sharding(mesh)
        host = _tiny_batch(mesh)
        dev = {k: jax.device_put(v, bs) for k, v in host.items()}
        flat, counts_next, dropped_next = pack_batch_sharded_ring(
            dev["shapes"], dev["counts"], dev["dropped"], dev["totals"],
            dev["reserved0"], dev["valid"], dev["last_valid"],
            dev["pods_unit"], num_iters=16, mesh=mesh, kernel="xla")
        np.asarray(flat)  # materialize: donation is now final
        for name in ("counts", "dropped"):
            assert dev[name].is_deleted(), name
            with pytest.raises(RuntimeError):
                np.asarray(dev[name])
        # the outputs own that memory and are positioned as the next
        # chunk's inputs: shape/dtype match and they are readable
        assert counts_next.shape == host["counts"].shape
        assert np.asarray(dropped_next).sum() == 0

    def test_fetch_twice_returns_cached_results(self, fresh_watchdog):
        """A second fetch() on a dispatched batch must return the SAME
        cached results — it must never re-enter the device path, whose
        input buffers were donated away by the first fetch."""
        from karpenter_tpu.cloudprovider.fake.provider import instance_types
        from karpenter_tpu.solver.batch_solve import Problem, dispatch_batch

        catalog = instance_types(6)
        constraints = universe_constraints(catalog)
        pods = make_pods(3, n=16)
        for p in pods:
            p.spec.node_selector = {}
        handle = dispatch_batch(
            [Problem(constraints=constraints, pods=pods,
                     instance_types=catalog)],
            SolverConfig(device_min_pods=1, device_donate=True))
        first = handle.fetch()
        second = handle.fetch()
        assert second is first
        assert first[0].node_count > 0
