"""Packing-policy scoring: scalar registry + device window kernel.

Three contracts under test:

- the DEFAULT policy is bit-for-bit the pre-policy behavior: ``cheapest``
  delegates structurally to models/cost.py (same floats, same ordering),
  and a full solve under it is identical with device scoring on and off
  (differential across seeds 1/7/42);
- the device window kernel (ops/policy.score_fused_window) produces
  pre-encoded rows equal to encode_prices over the host per-cell loop for
  penalty-free policies, honors the KARPENTER_POLICY_DEVICE kill switch,
  and never lets an unverified score through (zero score-mismatch
  fallbacks on clean runs);
- the interruption-priced algebra: spot wins exactly when
  ``rate x repack < price x (1 - spot_factor)``, with the repack cost
  priced by the what-if engine (0 when displaced pods refit on free
  capacity, else the cheapest on-demand replacement).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import NodeSelectorRequirement as Req
from karpenter_tpu.cloudprovider.fake.provider import make_instance_type
from karpenter_tpu.cloudprovider.spi import Offering
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.metrics.policy import POLICY_FALLBACK_TOTAL
from karpenter_tpu.models.cost import (
    CostConfig, effective_price, order_options_by_price,
)
from karpenter_tpu.models.ffd import encode_prices
from karpenter_tpu.ops import device_filter
from karpenter_tpu.ops import policy as ops_policy
from karpenter_tpu.solver import policy as policy_registry
from karpenter_tpu.solver.adapter import marshal_pods_interned
from karpenter_tpu.solver.batch_solve import Problem, solve_batch
from karpenter_tpu.solver.policy import (
    PolicyContext, whatif_repack_cost,
)
from karpenter_tpu.solver.solve import (
    SolverConfig, resolved_device_max_shapes,
)
from tests.test_batch_solve import result_key
from tests.test_pack_parity import make_pod


def _catalog(n=12, seed=0):
    """Priced catalog with spot offerings carrying interruption rates."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        cpu = rng.choice([2, 4, 8, 16, 32])
        out.append(make_instance_type(
            name=f"t{i}-{cpu}c", cpu=str(cpu), memory=f"{cpu * 4}Gi",
            pods=str(cpu * 8), price=round(0.04 * cpu * rng.uniform(0.8, 1.3), 4),
            offerings=[
                Offering(ct, f"zone-{z + 1}",
                         interruption_rate=(round(rng.uniform(0.01, 0.2), 4)
                                            if ct == "spot" else 0.0))
                for z in range(2) for ct in ("on-demand", "spot")]))
    return out


def _problems(catalog, seed, n=4):
    rng = random.Random(seed)
    constraints = universe_constraints(catalog)
    problems = []
    for b in range(n):
        tightened = constraints.deepcopy()
        tightened.requirements = tightened.requirements.add(Req(
            key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
            values=[f"zone-{1 + b % 2}"]))
        pods = []
        for j in range(rng.randint(40, 120)):
            pods.append(make_pod({
                "cpu": f"{rng.choice([100, 250, 500, 1000])}m",
                "memory": f"{rng.choice([128, 512, 1024])}Mi"}))
            pods[-1].metadata.name = f"p{b}-{j}"
        problems.append(Problem(constraints=tightened, pods=pods,
                                instance_types=catalog))
    return problems


class TestDefaultDelegation:
    """``cheapest`` must be the pre-policy float path, not a re-derivation."""

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_score_is_effective_price(self, seed):
        catalog = _catalog(seed=seed)
        cons = universe_constraints(catalog)
        policy = policy_registry.get("cheapest")
        ctx = PolicyContext()
        cfg = CostConfig()
        for it in catalog:
            assert policy.score(it, cons.requirements, cfg, ctx) \
                == effective_price(it, cons.requirements, cfg)

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_ordering_is_order_options_by_price(self, seed):
        catalog = _catalog(seed=seed)
        cons = universe_constraints(catalog)
        policy = policy_registry.get("cheapest")
        got = policy.order_options(list(catalog), cons.requirements,
                                   CostConfig(), PolicyContext())
        want = order_options_by_price(list(catalog), cons.requirements,
                                      CostConfig())
        assert [it.name for it in got] == [it.name for it in want]


class TestDeviceWindowParity:
    def _fused(self, problems, config):
        marshaled = [marshal_pods_interned(p.pods) for p in problems]
        return device_filter.prepare_fused(
            problems, marshaled, config, resolved_device_max_shapes(config))

    @pytest.mark.parametrize("seed", [1, 7, 42])
    @pytest.mark.parametrize("name", ["cheapest", "throughput-per-dollar"])
    def test_penalty_free_rows_bit_for_bit(self, seed, name):
        """Penalty-free policies: the device row must equal encode_prices
        of the host per-cell scores exactly — min-over-offerings commutes
        with the monotone micro-$ encoding."""
        catalog = _catalog(seed=seed)
        config = SolverConfig(device_min_pods=1)
        problems = _problems(catalog, seed)
        fused = self._fused(problems, config)
        if fused is None:
            pytest.skip("no device backend for the fused window")
        try:
            policy = policy_registry.get(name)
            ctx = PolicyContext(throughput={catalog[0].name: 2.0,
                                            catalog[1].name: 0.5})
            rows = ops_policy.score_fused_window(
                fused, policy, config.cost_config, ctx)
            assert rows is not None
            planes = device_filter.planes_for(fused.uni_types)
            for b, i in enumerate(fused.batch_idx):
                reqs = problems[i].constraints.requirements
                want = encode_prices(
                    [policy.score(fused.uni_types[p.index], reqs,
                                  config.cost_config, ctx)[0]
                     for p in fused.packables], planes.TB)
                assert np.array_equal(rows[b], want), \
                    f"member {i} row diverged from the host loop"
        finally:
            fused.release()

    def test_kill_switch_returns_none(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_POLICY_DEVICE", "0")
        assert not ops_policy.enabled()
        catalog = _catalog()
        config = SolverConfig(device_min_pods=1)
        problems = _problems(catalog, 1)
        fused = self._fused(problems, config)
        if fused is None:
            pytest.skip("no device backend for the fused window")
        try:
            assert ops_policy.score_fused_window(
                fused, policy_registry.get("cheapest"),
                config.cost_config, PolicyContext()) is None
        finally:
            fused.release()

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_solve_differential_device_vs_host_scoring(self, seed,
                                                       monkeypatch):
        """The whole solve under the default policy with the cost
        tie-break on: device window scoring vs the per-cell host loop
        must be result-identical, problem for problem — and the run must
        not burn a single score-mismatch fallback."""
        catalog = _catalog(seed=seed)
        problems = _problems(catalog, seed)
        config = SolverConfig(device_min_pods=1, cost_tiebreak=True)
        mm_key = (("reason", "score-mismatch"),)
        before = POLICY_FALLBACK_TOTAL.collect().get(mm_key, 0.0)
        monkeypatch.setenv("KARPENTER_POLICY_DEVICE", "1")
        on = solve_batch(problems, config)
        monkeypatch.setenv("KARPENTER_POLICY_DEVICE", "0")
        off = solve_batch(problems, config)
        assert [result_key(r) for r in on] == [result_key(r) for r in off]
        assert POLICY_FALLBACK_TOTAL.collect().get(mm_key, 0.0) == before


class TestInterruptionPriced:
    def test_frontier_break_even(self):
        """ct flips from spot to on-demand exactly at
        rate x repack = price x (1 - factor)."""
        P, r = 1.0, 0.5
        it = make_instance_type(
            name="fr", cpu="4", memory="8Gi", pods="16", price=P,
            offerings=[Offering("on-demand", "zone-1"),
                       Offering("spot", "zone-1", interruption_rate=r)])
        cons = universe_constraints([it])
        cfg = CostConfig()
        policy = policy_registry.get("interruption-priced")
        threshold = P * (1.0 - cfg.spot_price_factor) / r
        for mult, want in ((0.0, "spot"), (0.5, "spot"), (0.99, "spot"),
                           (1.01, "on-demand"), (3.0, "on-demand")):
            ctx = PolicyContext(repack_cost_per_hour=threshold * mult)
            _, ct = policy.score(it, cons.requirements, cfg, ctx)
            assert ct == want, f"mult={mult}: got {ct}"

    def test_requirements_pin_wins_over_price(self):
        it = make_instance_type(
            name="pinned", cpu="4", memory="8Gi", pods="16", price=1.0,
            offerings=[Offering("on-demand", "zone-1"),
                       Offering("spot", "zone-1", interruption_rate=9.0)])
        cons = universe_constraints([it])
        cons.requirements = cons.requirements.add(Req(
            key=wellknown.LABEL_CAPACITY_TYPE, operator="In",
            values=[wellknown.CAPACITY_TYPE_SPOT]))
        policy = policy_registry.get("interruption-priced")
        # a huge reclaim tax cannot un-pin an explicit spot requirement
        _, ct = policy.score(it, cons.requirements, CostConfig(),
                             PolicyContext(repack_cost_per_hour=100.0))
        assert ct == wellknown.CAPACITY_TYPE_SPOT


class TestWhatIfRepackCost:
    def _vec(self, cpu_n, mem, pods_n=1):
        from karpenter_tpu.solver.host_ffd import (
            NUM_RESOURCES, POD_UNIT_NANO, R_CPU, R_MEMORY, R_PODS,
        )
        v = [0] * NUM_RESOURCES
        v[R_CPU], v[R_MEMORY] = cpu_n, mem
        v[R_PODS] = pods_n * POD_UNIT_NANO
        return v

    def test_refit_on_free_capacity_is_free(self):
        catalog = _catalog()
        cons = universe_constraints(catalog)
        pod = self._vec(500 * 10**6, 512 << 20)
        free = self._vec(4 * 10**9, 8 << 30, 10)
        assert whatif_repack_cost([pod], [free], catalog,
                                  cons.requirements) == 0.0

    def test_no_refit_prices_cheapest_on_demand(self):
        catalog = _catalog()
        cons = universe_constraints(catalog)
        pod = self._vec(2 * 10**9, 1 << 30)
        cost = whatif_repack_cost([pod], [], catalog, cons.requirements)
        want = min(it.price for it in catalog
                   if any(o.capacity_type == "on-demand"
                          for o in it.offerings))
        assert cost == want

    def test_empty_displacement_is_free(self):
        catalog = _catalog()
        cons = universe_constraints(catalog)
        assert whatif_repack_cost([], [], catalog, cons.requirements) == 0.0


class TestThroughputPerDollar:
    def test_orders_by_price_per_throughput(self):
        a = make_instance_type(name="fast", cpu="8", memory="16Gi",
                               pods="32", price=2.0)
        b = make_instance_type(name="slow", cpu="8", memory="16Gi",
                               pods="32", price=1.0)
        cons = universe_constraints([a, b])
        policy = policy_registry.get("throughput-per-dollar")
        # fast does 4x the work at 2x the price: it must win
        ctx = PolicyContext(throughput={"fast": 4.0, "slow": 1.0})
        got = policy.order_options([a, b], cons.requirements, CostConfig(),
                                   ctx)
        assert [it.name for it in got] == ["fast", "slow"]
        # no table: degrades to cheapest-feasible ordering
        got = policy.order_options([a, b], cons.requirements, CostConfig(),
                                   PolicyContext())
        assert [it.name for it in got] == ["slow", "fast"]

    def test_zero_throughput_never_wins(self):
        a = make_instance_type(name="dead", cpu="8", memory="16Gi",
                               pods="32", price=0.1)
        cons = universe_constraints([a])
        policy = policy_registry.get("throughput-per-dollar")
        score, ct = policy.score(a, cons.requirements, CostConfig(),
                                 PolicyContext(throughput={"dead": 0.0}))
        assert score == float("inf") and ct is None
