"""Anti-thrash preemption budget (scheduling/preempt_budget.py).

Pins the two guards ISSUE 19 adds in front of priced preemption:

- per-band token bucket: a band's candidates are truncated to its
  available tokens (cheapest displacement first), tokens refill one per
  gang window up to capacity, and an executed displacement consumes one;
- per-gang cooldown: a gang displaced once is filtered out of every
  preempt context for the next N windows, then becomes eligible again;
- a saturated repeat-window flood converges: no gang is ever displaced
  twice within the cooldown, and per-window displacements never exceed
  the band cap;
- declines surface on ``karpenter_preemption_budget_declines_total``
  (tokens | cooldown) and as reason="budget" on
  ``karpenter_preemption_declined_total``.
"""

import numpy as np

from karpenter_tpu.metrics.topology import (
    PREEMPTION_BUDGET_DECLINES_TOTAL, PREEMPTION_DECLINED_TOTAL,
)
from karpenter_tpu.scheduling.preempt_budget import PreemptionBudget
from karpenter_tpu.solver.gang import PreemptCandidate


def _count(metric, **labels) -> float:
    return metric.collect().get(tuple(sorted(labels.items())), 0.0)


def _cand(gang, band="low", cost=0.1):
    return PreemptCandidate(
        gang_key=gang, bin_index=0, node="n1", band=band,
        pods=[("d", f"{gang}-m0")], cells=np.arange(4),
        refund=[1, 1], displacement_cost=cost)


class TestTokenBucket:
    def test_starts_full_and_admits_up_to_capacity(self):
        b = PreemptionBudget(capacity={"low": 2}, cooldown_windows=0)
        cands = [_cand(f"g{i}", cost=0.1 * i) for i in range(4)]
        out = b.admit(cands)
        assert [c.gang_key for c in out] == ["g0", "g1"]

    def test_truncation_keeps_cheapest_not_first(self):
        b = PreemptionBudget(capacity={"low": 1})
        expensive = _cand("pricey", cost=9.0)
        cheap = _cand("bargain", cost=0.1)
        out = b.admit([expensive, cheap])
        assert [c.gang_key for c in out] == ["bargain"]

    def test_charge_consumes_and_tick_refills_to_cap(self):
        b = PreemptionBudget(capacity={"low": 2}, refill_per_window=1,
                             cooldown_windows=0)
        b.charge("g0", "low")
        b.charge("g1", "low")
        assert b.tokens("low") == 0
        assert b.admit([_cand("g2")]) == []
        b.tick()
        assert b.tokens("low") == 1
        b.tick()
        b.tick()
        assert b.tokens("low") == 2  # capped, never above capacity

    def test_unknown_band_is_not_throttled(self):
        b = PreemptionBudget(capacity={"low": 0})
        exotic = _cand("g0", band="exotic-band")
        assert b.admit([exotic]) == [exotic]

    def test_decline_metrics(self):
        t0 = _count(PREEMPTION_BUDGET_DECLINES_TOTAL, reason="tokens")
        bud0 = _count(PREEMPTION_DECLINED_TOTAL, reason="budget")
        b = PreemptionBudget(capacity={"low": 0})
        assert b.admit([_cand("g0")]) == []
        assert _count(PREEMPTION_BUDGET_DECLINES_TOTAL,
                      reason="tokens") == t0 + 1
        assert _count(PREEMPTION_DECLINED_TOTAL, reason="budget") == bud0 + 1


class TestCooldown:
    def test_displaced_gang_is_filtered_for_n_windows(self):
        c0 = _count(PREEMPTION_BUDGET_DECLINES_TOTAL, reason="cooldown")
        b = PreemptionBudget(capacity={"low": 8}, cooldown_windows=2)
        b.charge("victim", "low")
        assert b.in_cooldown("victim")
        for _ in range(2):
            b.tick()
            assert b.admit([_cand("victim")]) == []
        assert _count(PREEMPTION_BUDGET_DECLINES_TOTAL,
                      reason="cooldown") == c0 + 2
        b.tick()  # cooldown elapsed
        assert not b.in_cooldown("victim")
        assert [c.gang_key for c in b.admit([_cand("victim")])] == ["victim"]

    def test_cooldown_is_per_gang(self):
        b = PreemptionBudget(capacity={"low": 8}, cooldown_windows=3)
        b.charge("a", "low")
        out = b.admit([_cand("a"), _cand("b")])
        assert [c.gang_key for c in out] == ["b"]


class TestFloodConverges:
    def test_no_gang_displaced_twice_within_cooldown(self):
        """Saturated repeat-window flood: every window offers every
        resident as a candidate; the budget must (1) never let one gang
        be displaced twice within the cooldown and (2) never exceed the
        band cap per window."""
        cooldown = 3
        b = PreemptionBudget(capacity={"low": 2}, refill_per_window=2,
                             cooldown_windows=cooldown)
        last_hit = {}
        for window in range(1, 21):
            b.tick()
            admitted = b.admit([_cand(f"g{i}", cost=0.1) for i in range(6)])
            assert len(admitted) <= 2  # band cap per window
            for c in admitted:
                key = str(c.gang_key)
                if key in last_hit:
                    assert window - last_hit[key] > cooldown, \
                        f"{key} displaced twice within cooldown"
                last_hit[key] = window
                b.charge(c.gang_key, c.band)
        assert last_hit  # the flood did displace, it just never thrashed
