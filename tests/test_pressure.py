"""Brownout subsystem: bands, ladder hysteresis, bounded priority intake.

Covers the invariants docs/robustness.md §4 promises:

- classification is derived from the same fields the kube scheduler uses;
- the ladder rises immediately and falls one rung per dwell (hysteresis —
  an oscillating signal parks at the higher rung);
- shedding policy per rung, with system-critical never shed and aging
  promotion preventing starvation;
- the batcher's depth bound sheds (or displaces for system-critical)
  instead of growing without bound, and a shed pod's key is released
  immediately;
- window order is a pure function of the pod set — any arrival
  interleaving of the same pods yields the same order (parity);
- the seeded chaos kinds (queue-flood / memory-pressure / slow-apiserver)
  drive the monitor and the kube shim deterministically.
"""

import random
import threading
import time

import pytest

from karpenter_tpu.api.core import ObjectMeta, Pod, PodSpec
from karpenter_tpu.chaos import inject
from karpenter_tpu.metrics import registry
from karpenter_tpu.pressure import bands
from karpenter_tpu.pressure.monitor import (
    PressureConfig, PressureLevel, PressureMonitor,
)
from karpenter_tpu.scheduling.batcher import Batcher
from tests.expectations import unschedulable_pod


class FakeMonitor:
    """Deterministic monitor stand-in for batcher tests: a fixed level and
    the real config object (thresholds, aging step, split size)."""

    def __init__(self, level=0, aging_step_seconds=60.0, max_depth=100_000):
        self.config = PressureConfig(max_depth=max_depth,
                                     aging_step_seconds=aging_step_seconds)
        self._level = level

    def level(self):
        return self._level

    def note_depth(self, source, depth):
        pass

    def note_window(self, seconds):
        pass

    def forget_source(self, source):
        pass


def _monitor(dwell=5.0, max_depth=100, watermark=0, **kw):
    """PressureMonitor on a fake clock with all ambient signals silenced."""
    t = [0.0]
    mon = PressureMonitor(
        PressureConfig(max_depth=max_depth, dwell_seconds=dwell,
                       rss_watermark_bytes=watermark, **kw),
        timefunc=lambda: t[0],
        breaker_fn=lambda: False,
        rss_fn=lambda: 0)
    return mon, t


# ---------------------------------------------------------------------------
# Band classification + policy
# ---------------------------------------------------------------------------


class TestBands:
    def test_system_critical_by_class_name(self):
        pod = unschedulable_pod(
            requests={"cpu": "100m"},
            priority_class_name="system-cluster-critical")
        assert bands.classify(pod) == ("system-critical", 0)

    def test_system_critical_by_priority_floor(self):
        pod = unschedulable_pod(requests={"cpu": "100m"},
                                priority=2_000_001_000)
        assert bands.classify(pod)[0] == "system-critical"

    def test_high_default_low(self):
        assert bands.classify(unschedulable_pod(
            requests={"cpu": "1"}, priority=100))[0] == "high"
        assert bands.classify(unschedulable_pod(
            requests={"cpu": "1"}))[0] == "default"
        assert bands.classify(unschedulable_pod(
            requests={"cpu": "1"}, priority=-10)) == ("low", -10)

    def test_besteffort_is_requestless(self):
        pod = Pod(metadata=ObjectMeta(name="be"), spec=PodSpec())
        assert bands.classify(pod)[0] == "besteffort"

    def test_non_pod_items_land_in_default(self):
        assert bands.classify("just-a-string") == ("default", 0)
        assert bands.classify(42) == ("default", 0)

    def test_shed_policy_matrix(self):
        R = bands.RANK
        for level in range(4):
            assert bands.shed_reason(R["system-critical"], level) is None
        for rank in (R["high"], R["default"]):
            assert bands.shed_reason(rank, 2) is None
            assert bands.shed_reason(rank, 3) == "pressure-l3"
        for rank in (R["low"], R["besteffort"]):
            assert bands.shed_reason(rank, 1) is None
            assert bands.shed_reason(rank, 2) == "pressure-l2"
            assert bands.shed_reason(rank, 3) == "pressure-l3"

    def test_aging_promotes_one_band_per_step_never_into_critical(self):
        R = bands.RANK
        assert bands.effective_rank(R["besteffort"], 0.0, 60.0) == 4
        assert bands.effective_rank(R["besteffort"], 59.9, 60.0) == 4
        assert bands.effective_rank(R["besteffort"], 60.0, 60.0) == 3
        assert bands.effective_rank(R["besteffort"], 1e9, 60.0) == 1
        assert bands.effective_rank(R["system-critical"], 1e9, 60.0) == 0
        # aging disabled
        assert bands.effective_rank(R["low"], 1e9, 0.0) == R["low"]


# ---------------------------------------------------------------------------
# Ladder hysteresis
# ---------------------------------------------------------------------------


class TestHysteresis:
    def test_rises_immediately(self):
        mon, t = _monitor()
        assert mon.evaluate() == PressureLevel.L0
        mon.note_depth(1, 90)  # >= depth_l3 (85% of 100)
        assert mon.evaluate() == PressureLevel.L3

    def test_burst_within_one_eval_window_still_rises(self):
        """A flood that fills the queue inside a single eval_interval
        window must still raise the ladder: level() serves a cached rung
        for 50 ms, but a depth sample crossing a rung threshold forces a
        re-evaluation — "rises immediately" must not depend on the intake
        loop being slow enough to straddle two eval windows."""
        mon, t = _monitor()
        assert mon.level() == PressureLevel.L0  # primes the eval cache
        # zero wall time passes: a plain level() would serve the cached L0
        mon.note_depth(1, 90)  # >= depth_l3 — the burst guard re-evaluates
        assert mon.level() == PressureLevel.L3

    def test_falls_one_rung_per_dwell(self):
        mon, t = _monitor(dwell=5.0)
        mon.note_depth(1, 60)  # >= depth_l2 (50)
        assert mon.evaluate() == PressureLevel.L2
        mon.note_depth(1, 0)
        t[0] = 1.0
        assert mon.evaluate() == PressureLevel.L2  # dwell not served yet
        t[0] = 5.9
        assert mon.evaluate() == PressureLevel.L2
        t[0] = 6.0
        assert mon.evaluate() == PressureLevel.L1  # one rung, not a cliff
        t[0] = 10.9
        assert mon.evaluate() == PressureLevel.L1
        t[0] = 11.0
        assert mon.evaluate() == PressureLevel.L0

    def test_oscillation_parks_at_the_higher_rung(self):
        mon, t = _monitor(dwell=5.0)
        for cycle in range(5):
            t[0] = cycle * 4.0
            mon.note_depth(1, 60)
            assert mon.evaluate() == PressureLevel.L2
            t[0] = cycle * 4.0 + 2.0
            mon.note_depth(1, 0)
            assert mon.evaluate() == PressureLevel.L2

    def test_rise_mid_dwell_resets_the_clock(self):
        mon, t = _monitor(dwell=5.0)
        mon.note_depth(1, 60)
        mon.evaluate()
        mon.note_depth(1, 0)
        t[0] = 4.0
        mon.evaluate()
        mon.note_depth(1, 95)  # spike back up
        t[0] = 4.5
        assert mon.evaluate() == PressureLevel.L3
        mon.note_depth(1, 0)
        t[0] = 9.0  # only 4.5 s below L3
        assert mon.evaluate() == PressureLevel.L3

    def test_disabled_pins_l0(self):
        mon, t = _monitor(enabled=False)
        mon.note_depth(1, 1000)
        assert mon.evaluate() == PressureLevel.L0
        assert mon.level() == PressureLevel.L0


class TestSignals:
    def test_depth_thresholds(self):
        mon, t = _monitor()
        mon.note_depth(1, 20)
        assert mon.evaluate() == PressureLevel.L1
        mon.note_depth(2, 30)  # summed across sources: 50 -> L2
        assert mon.evaluate() == PressureLevel.L2
        mon.forget_source(2)
        mon.forget_source(1)
        assert mon._target(t[0]) == PressureLevel.L0

    def test_window_signal_and_staleness(self):
        mon, t = _monitor()
        mon.note_window(6.0)  # >= window_l1 (5 s)
        assert mon.evaluate() == PressureLevel.L1
        mon.note_window(31.0)  # >= window_l2 (30 s)
        assert mon._target(t[0]) == PressureLevel.L2
        t[0] = 200.0  # past window_staleness_seconds — sample expires
        assert mon._target(t[0]) == PressureLevel.L0

    def test_throttle_accumulates_and_decays(self):
        mon, t = _monitor()
        mon.note_throttle(0.3)
        assert mon._target(t[0]) == PressureLevel.L0
        mon.note_throttle(0.3)  # accumulated 0.6 >= throttle_l1 (0.5)
        assert mon._target(t[0]) == PressureLevel.L1
        t[0] = 90.0  # 3 tau later: 0.6 * e^-3 ~ 0.03
        assert mon._target(t[0]) == PressureLevel.L0

    def test_breaker_maps_to_l1(self):
        state = {"open": True}
        mon = PressureMonitor(
            PressureConfig(max_depth=100, rss_watermark_bytes=0),
            timefunc=lambda: 0.0, breaker_fn=lambda: state["open"],
            rss_fn=lambda: 0)
        assert mon.evaluate() == PressureLevel.L1

    def test_rss_watermark(self):
        rss = {"v": 0}
        t = [0.0]
        mon = PressureMonitor(
            PressureConfig(max_depth=100, rss_watermark_bytes=1000),
            timefunc=lambda: t[0], breaker_fn=lambda: False,
            rss_fn=lambda: rss["v"])
        rss["v"] = 850  # 85% -> L2
        t[0] = 1.0
        assert mon.evaluate() == PressureLevel.L2
        rss["v"] = 1000  # at the watermark -> L3
        t[0] = 2.0
        assert mon.evaluate() == PressureLevel.L3

    def test_level_metric_exported(self):
        mon, _ = _monitor()
        mon.note_depth(1, 60)
        mon.evaluate()
        exported = registry.DEFAULT.expose()
        assert "karpenter_pressure_level{} 2.0" in exported


# ---------------------------------------------------------------------------
# Bounded, priority-ordered batcher intake
# ---------------------------------------------------------------------------


def _pod(name, **spec_kwargs):
    return unschedulable_pod(requests={"cpu": "100m"}, name=name,
                             **spec_kwargs)


class TestBatcherShedding:
    def test_l2_sheds_low_bands_and_releases_key(self):
        fm = FakeMonitor(level=2)
        b = Batcher(idle_seconds=0.01, max_seconds=0.1, monitor=fm)
        low = _pod("low-1", priority=-5)
        gate = b.add(low, key=("default", "low-1"), band="low", priority=-5)
        assert gate is None
        assert not b.contains(("default", "low-1"))  # released immediately
        assert b.shed == {("pressure-l2", "low"): 1}
        assert b.added_total == 0  # shed items never count as added

        # pressure falls: the same keyed pod is admitted on the requeue
        fm._level = 0
        gate = b.add(low, key=("default", "low-1"), band="low", priority=-5)
        assert gate is not None
        assert b.contains(("default", "low-1"))

    def test_l3_sheds_default_but_never_system_critical(self):
        fm = FakeMonitor(level=3)
        b = Batcher(idle_seconds=0.01, max_seconds=0.1, monitor=fm)
        assert b.add(_pod("d"), key=("default", "d")) is None
        crit = b.add(_pod("c"), key=("default", "c"),
                     band="system-critical", priority=2_000_001_000)
        assert crit is not None
        assert b.shed == {("pressure-l3", "default"): 1}

    def test_first_seen_survives_sheds_and_ages_into_admission(self):
        fm = FakeMonitor(level=2, aging_step_seconds=1.0)
        b = Batcher(idle_seconds=0.01, max_seconds=0.1, monitor=fm)
        key = ("default", "aged")
        now = time.monotonic()
        # simulate a pod that has been shed and requeued for 3 aging steps
        b._first_seen[key] = (now - 3.5, now)
        gate = b.add(_pod("aged", priority=-5), key=key, band="low",
                     priority=-5)
        assert gate is not None, (
            "an aged low-priority pod must be promoted past the L2 shed "
            "line — starvation freedom")

    def test_depth_bound_sheds_non_critical(self):
        b = Batcher(idle_seconds=0.01, max_seconds=0.1, max_depth=2,
                    monitor=FakeMonitor())
        assert b.add(_pod("a"), key=("default", "a")) is not None
        assert b.add(_pod("b"), key=("default", "b")) is not None
        assert b.add(_pod("c"), key=("default", "c")) is None
        assert b.shed == {("depth-bound", "default"): 1}
        assert not b.contains(("default", "c"))
        assert b.depth() == 2

    def test_depth_bound_displaces_for_system_critical(self):
        b = Batcher(idle_seconds=0.01, max_seconds=0.1, max_depth=2,
                    monitor=FakeMonitor())
        b.add(_pod("a"), key=("default", "a"))
        b.add(_pod("b"), key=("default", "b"))
        gate = b.add(_pod("crit"), key=("default", "crit"),
                     band="system-critical", priority=2_000_001_000)
        assert gate is not None
        assert b.depth() == 2
        assert b.shed == {("displaced", "default"): 1}
        # exactly one of the two defaults lost its slot AND its key
        pending = [k for k in (("default", "a"), ("default", "b"))
                   if b.contains(k)]
        assert len(pending) == 1
        assert b.contains(("default", "crit"))

    def test_all_critical_queue_overflows_the_bound(self):
        b = Batcher(idle_seconds=0.01, max_seconds=0.1, max_depth=1,
                    monitor=FakeMonitor())
        b.add(_pod("c1"), key=("default", "c1"), band="system-critical")
        gate = b.add(_pod("c2"), key=("default", "c2"),
                     band="system-critical")
        assert gate is not None  # admitted over the bound, never shed
        assert b.depth() == 2
        assert b.shed == {}


class TestWindowOrder:
    def _mixed_pods(self):
        pods = []
        for i in range(4):
            pods.append((_pod(f"crit-{i}",
                              priority_class_name="system-cluster-critical"),
                         "system-critical", 2_000_001_000))
            pods.append((_pod(f"high-{i}", priority=100 - i), "high", 100 - i))
            pods.append((_pod(f"def-{i}"), "default", 0))
            pods.append((_pod(f"low-{i}", priority=-1 - i), "low", -1 - i))
        return pods

    def _window_for(self, order):
        b = Batcher(idle_seconds=0.01, max_seconds=0.2,
                    monitor=FakeMonitor())
        for pod, band, prio in order:
            b.add(pod, key=(pod.metadata.namespace, pod.metadata.name),
                  band=band, priority=prio)
        items, _ = b.wait()
        b.stop()
        return [p.metadata.name for p in items]

    def test_priority_order_parity_across_interleavings(self):
        """Same pod set, ANY arrival interleaving -> the identical window
        order: rank, then priority value desc, then stable pod identity —
        never arrival sequence."""
        pods = self._mixed_pods()
        reference = self._window_for(pods)
        # bands come out strictly in rank order
        rank_seq = [bands.RANK[b] for b in
                    ("system-critical",) * 4 + ("high",) * 4
                    + ("default",) * 4 + ("low",) * 4]
        got_ranks = []
        for name in reference:
            band = {"crit": "system-critical", "high": "high",
                    "def": "default", "low": "low"}[name.split("-")[0]]
            got_ranks.append(bands.RANK[band])
        assert got_ranks == rank_seq
        # high band is ordered by priority value, descending
        highs = [n for n in reference if n.startswith("high-")]
        assert highs == ["high-0", "high-1", "high-2", "high-3"]
        for seed in (1, 7, 42):
            shuffled = list(pods)
            random.Random(seed).shuffle(shuffled)
            assert self._window_for(shuffled) == reference, (
                f"arrival interleaving (seed={seed}) changed window order")

    def test_shed_metric_counts_by_reason_and_band(self):
        b = Batcher(idle_seconds=0.01, max_seconds=0.1,
                    monitor=FakeMonitor(level=2))
        before = dict(b.shed)
        assert before == {}
        b.add(_pod("be-x"), band="besteffort")
        b.add(_pod("lo-x", priority=-1), band="low", priority=-1)
        assert b.shed == {("pressure-l2", "besteffort"): 1,
                          ("pressure-l2", "low"): 1}
        assert b.shed_total() == 2
        assert b.shed_total(band="low") == 1
        exported = registry.DEFAULT.expose()
        assert "karpenter_pods_shed_total" in exported


# ---------------------------------------------------------------------------
# Chaos kinds
# ---------------------------------------------------------------------------


class TestChaosKinds:
    def test_queue_flood_inflates_the_depth_sample(self):
        mon, t = _monitor()
        inject.install(inject.FaultPlan(3, [
            inject.FaultSpec("pressure", "depth", "queue-flood", 1)],
            window=1))
        try:
            # max_depth=100 -> +50 synthetic depth -> depth_l2 -> L2
            assert mon.evaluate() == PressureLevel.L2
        finally:
            inject.uninstall()

    def test_memory_pressure_inflates_the_rss_sample(self):
        t = [0.0]
        mon = PressureMonitor(
            PressureConfig(max_depth=100, rss_watermark_bytes=1000),
            timefunc=lambda: t[0], breaker_fn=lambda: False,
            rss_fn=lambda: 100)
        inject.install(inject.FaultPlan(3, [
            inject.FaultSpec("pressure", "rss", "memory-pressure", 1)],
            window=1))
        try:
            # 100 real + 870 synthetic = 970 >= 85% of 1000 -> L2
            assert mon.evaluate() == PressureLevel.L2
        finally:
            inject.uninstall()
        # the fault fired exactly once: the next evaluation is clean
        t[0] = 10.0
        assert mon.evaluate() == PressureLevel.L2  # hysteresis holds it...
        t[0] = 20.0
        mon.evaluate()
        t[0] = 30.0
        assert mon.evaluate() == PressureLevel.L0  # ...then it drains

    def test_slow_apiserver_stalls_but_succeeds(self, monkeypatch):
        from karpenter_tpu.runtime.kubecore import KubeCore

        monkeypatch.setattr(inject.ChaosKube, "SLOW_APISERVER_STALL_S", 0.05)
        kube = inject.ChaosKube(KubeCore())
        inject.install(inject.FaultPlan(5, [
            inject.FaultSpec("kube", "create", "slow-apiserver", 1)],
            window=1))
        try:
            start = time.monotonic()
            kube.create(_pod("slow"))
        finally:
            inject.uninstall()
        assert time.monotonic() - start >= 0.05
        assert kube.get("Pod", "slow") is not None  # the write LANDED


# ---------------------------------------------------------------------------
# Level-aware window shrink
# ---------------------------------------------------------------------------


class TestWindowShrink:
    def test_l1_halves_the_windows(self):
        fm = FakeMonitor(level=1)
        b = Batcher(idle_seconds=0.2, max_seconds=2.0, monitor=fm)
        b.add("x")
        start = time.monotonic()
        items, _ = b.wait()
        elapsed = time.monotonic() - start
        b.stop()
        assert items == ["x"]
        # idle window halves at L1: 0.1 s, not 0.2 s (generous ceiling for
        # slow CI hosts — the unhalved window would be >= 0.2)
        from tests.expectations import host_loaded

        if not host_loaded("L1 window-shrink timing"):
            assert elapsed < 0.19, \
                f"window did not shrink at L1: {elapsed:.3f}s"
