"""End-to-end provisioning: pending pods → TPU solve → nodes created →
pods bound. Mirrors provisioning/suite_test.go ("should provision nodes").
"""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints, Limits, Taints
from karpenter_tpu.api.core import (
    DaemonSet, DaemonSetSpec, NodeSelectorRequirement as Req, ObjectMeta,
    PodTemplateSpec, PodSpec, Container, ResourceRequirements, Taint, Toleration,
)
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.utils.resources import parse_resource_list

from tests.expectations import (
    daemonset_pod_owned, expect_not_scheduled, expect_provisioned,
    expect_scheduled, make_provisioner, unschedulable_pod,
)


@pytest.fixture()
def env():
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=instance_types(10))
    provisioning = ProvisioningController(
        kube, provider,
        batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
    selection = SelectionController(kube, provisioning, gate_timeout=30.0)
    yield kube, provider, provisioning, selection
    for w in provisioning.workers.values():
        w.stop()


def setup_provisioner(kube, provisioning, **spec_kwargs):
    provisioner = make_provisioner(**spec_kwargs)
    kube.create(provisioner)
    provisioning.reconcile(provisioner.metadata.name)
    return provisioner


class TestProvisioning:
    def test_should_provision_nodes(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod() for _ in range(5)]
        expect_provisioned(kube, selection, provisioning, pods)
        for pod in pods:
            expect_scheduled(kube, pod)
        assert len(provider.created) >= 1
        node = kube.get("Node", provider.created[0].metadata.name, "")
        assert wellknown.TERMINATION_FINALIZER in node.metadata.finalizers
        assert any(t.key == wellknown.NOT_READY_TAINT_KEY for t in node.spec.taints)
        assert node.metadata.labels[wellknown.PROVISIONER_NAME_LABEL] == "default"

    def test_groups_pods_onto_shared_nodes(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod(requests={"cpu": "100m", "memory": "64Mi"})
                for _ in range(20)]
        expect_provisioned(kube, selection, provisioning, pods)
        nodes = {expect_scheduled(kube, p) for p in pods}
        # 20 tiny pods need far fewer than 20 nodes
        assert 1 <= len(nodes) < 10

    def test_ignores_daemonset_owned_pods(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = daemonset_pod_owned({"cpu": "1"})
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)
        assert provider.created == []

    def test_respects_node_selector_zone(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod(node_selector={
            wellknown.LABEL_TOPOLOGY_ZONE: "test-zone-2"})]
        expect_provisioned(kube, selection, provisioning, pods)
        node_name = expect_scheduled(kube, pods[0])
        node = kube.get("Node", node_name, "")
        assert node.metadata.labels[wellknown.LABEL_TOPOLOGY_ZONE] == "test-zone-2"

    def test_rejects_unknown_node_selector(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod(node_selector={"unknown-label": "x"})
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_taints_block_intolerant_pods(self, env):
        kube, provider, provisioning, selection = env
        constraints = Constraints(taints=Taints([Taint(key="dedicated", value="ml",
                                                       effect="NoSchedule")]))
        setup_provisioner(kube, provisioning, constraints=constraints)
        intolerant = unschedulable_pod()
        tolerant = unschedulable_pod(tolerations=[
            Toleration(key="dedicated", operator="Equal", value="ml",
                       effect="NoSchedule")])
        kube.create(intolerant)
        selection.reconcile(intolerant.metadata.name)
        expect_not_scheduled(kube, intolerant)
        expect_provisioned(kube, selection, provisioning, [tolerant])
        expect_scheduled(kube, tolerant)

    def test_limits_cap_provisioning(self, env):
        kube, provider, provisioning, selection = env
        provisioner = make_provisioner(
            limits=Limits(resources=parse_resource_list({"cpu": "1"})))
        # simulate counter controller: usage already at the cap
        provisioner.status.resources = parse_resource_list({"cpu": "10"})
        kube.create(provisioner)
        provisioning.reconcile(provisioner.metadata.name)
        pods = [unschedulable_pod()]
        expect_provisioned(kube, selection, provisioning, pods)
        expect_not_scheduled(kube, pods[0])
        assert provider.created == []

    def test_daemonset_overhead_accounted(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        kube.create(DaemonSet(
            metadata=ObjectMeta(name="logging"),
            spec=DaemonSetSpec(template=PodTemplateSpec(spec=PodSpec(containers=[
                Container(resources=ResourceRequirements.make(
                    requests={"cpu": "500m", "memory": "256Mi"}))])))))
        pods = [unschedulable_pod(requests={"cpu": "1", "memory": "512Mi"})]
        expect_provisioned(kube, selection, provisioning, pods)
        expect_scheduled(kube, pods[0])

    def test_deleted_pod_not_provisioned(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod()
        # never created in the API: the provisionability re-check drops it
        kube.create(pod)
        kube.delete("Pod", pod.metadata.name, pod.metadata.namespace)
        selection.reconcile(pod.metadata.name)
        assert provider.created == []

    def test_multiple_provisioners_first_match_wins(self, env):
        kube, provider, provisioning, selection = env
        c1 = Constraints(taints=Taints([Taint(key="a", value="1", effect="NoSchedule")]))
        p1 = make_provisioner(name="tainted", constraints=c1)
        kube.create(p1)
        provisioning.reconcile("tainted")
        setup_provisioner(kube, provisioning, name="open")
        pods = [unschedulable_pod()]
        expect_provisioned(kube, selection, provisioning, pods)
        node = kube.get("Node", expect_scheduled(kube, pods[0]), "")
        assert node.metadata.labels[wellknown.PROVISIONER_NAME_LABEL] == "open"


class TestStatusConditions:
    """The living condition set (provisioner_status.go:38-49,
    register.go:51-54): kubectl get provisioner shows readiness, plus this
    framework's solver-health signal (executor ring + breaker state)."""

    def test_active_and_solver_conditions_set(self, env):
        from karpenter_tpu.api.provisioner import get_condition

        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        p = kube.get("Provisioner", "default")
        active = get_condition(p.status.conditions, "Active")
        assert active is not None and active.status == "True"
        assert active.reason == "WorkerRunning"
        solver = get_condition(p.status.conditions, "SolverHealthy")
        assert solver is not None and solver.status == "True"

    def test_solver_condition_names_executor_after_solve(self, env):
        from karpenter_tpu.api.provisioner import get_condition

        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod() for _ in range(3)]
        expect_provisioned(kube, selection, provisioning, pods)
        provisioning.reconcile("default")  # refresh conditions post-solve
        p = kube.get("Provisioner", "default")
        solver = get_condition(p.status.conditions, "SolverHealthy")
        assert solver.status == "True"
        assert "executor=" in solver.message

    def test_breaker_open_flips_solver_condition(self, env, monkeypatch):
        from karpenter_tpu.api.provisioner import get_condition
        from karpenter_tpu.solver import solve as solve_module

        kube, provider, provisioning, selection = env
        monkeypatch.setattr(solve_module._WATCHDOG, "tripped", lambda: True)
        setup_provisioner(kube, provisioning)
        p = kube.get("Provisioner", "default")
        solver = get_condition(p.status.conditions, "SolverHealthy")
        assert solver.status == "False"
        assert solver.reason == "DeviceCircuitOpen"

    def test_condition_refresh_does_not_loop(self, env):
        """An unchanged condition set must not write (and so not emit a
        MODIFIED watch event the controller would chase forever) — even
        between solves, whose volatile stats must stay OUT of the message
        (each one fans out through the node controller's provisioner→nodes
        mapping otherwise)."""
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        rv1 = kube.get("Provisioner", "default").metadata.resource_version
        provisioning.reconcile("default")
        rv2 = kube.get("Provisioner", "default").metadata.resource_version
        assert rv1 == rv2
        # a solve happened; executor unchanged → still no status write
        pods = [unschedulable_pod() for _ in range(2)]
        expect_provisioned(kube, selection, provisioning, pods)
        provisioning.reconcile("default")
        rv3 = kube.get("Provisioner", "default").metadata.resource_version
        provisioning.reconcile("default")
        rv4 = kube.get("Provisioner", "default").metadata.resource_version
        assert rv3 == rv4

    def test_status_conditions_round_trip_codec(self):
        from karpenter_tpu.api.codec import (
            provisioner_from_manifest, provisioner_to_manifest,
        )
        from karpenter_tpu.api.provisioner import get_condition, set_condition

        p = make_provisioner()
        set_condition(p.status.conditions, "Active", "True", "WorkerRunning",
                      "provisioner worker running")
        manifest = provisioner_to_manifest(p)
        assert manifest["status"]["conditions"][0]["type"] == "Active"
        back = provisioner_from_manifest(manifest)
        cond = get_condition(back.status.conditions, "Active")
        assert cond.status == "True" and cond.reason == "WorkerRunning"


class TestBindErrors:
    """_bind must propagate real bind failures (provisioner.go:159-198 logs
    and drops them; here the joined error rides CloudProvider.create back to
    the provision loop) while treating already-bound pods as success."""

    def _worker(self, kube):
        from karpenter_tpu.controllers.provisioning import ProvisionerWorker

        provider = FakeCloudProvider(catalog=instance_types(4))
        return ProvisionerWorker(make_provisioner(), kube, provider)

    def test_missing_pod_error_propagates_joined(self):
        from karpenter_tpu.api.core import Node, Pod

        kube = KubeCore()
        worker = self._worker(kube)
        ghost = Pod(metadata=ObjectMeta(name="never-created"))
        err = worker._bind(Node(metadata=ObjectMeta(name="n1", namespace="")),
                           [ghost])
        assert err is not None and "not found" in err
        # the failed pod count and node name survive into the message
        assert "1 pod(s)" in err and "n1" in err

    def test_already_bound_pod_is_idempotent_success(self):
        from karpenter_tpu.api.core import Node

        kube = KubeCore()
        worker = self._worker(kube)
        pod = unschedulable_pod(name="bound-once")
        kube.create(pod)
        kube.bind_pods([pod], "elsewhere")
        # a stale provisionable read re-batched it: binding again must not
        # surface an error (it would relaunch capacity every window)
        err = worker._bind(Node(metadata=ObjectMeta(name="n2", namespace="")),
                           [pod])
        assert err is None
        assert kube.get("Pod", "bound-once").spec.node_name == "elsewhere"
