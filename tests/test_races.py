"""Concurrency stress: the Go `-race` analog (SURVEY §5.2, VERDICT r3).

Python has no race detector; these tests instead hammer each shared-state
hotspot from many threads and assert the invariants that a data race would
break — lost items, double delivery, torn counters, inconsistent intern
mappings, deadlocks. Round 3's one failing test was exactly a
thread-teardown race (watch severing); this module makes the remaining
shared state earn its locks:

- Batcher: concurrent add vs wait/flush windows (counters, no loss/dup)
- Manager _WorkQueue: processing exclusivity + dirty re-add (no lost keys)
- Device watchdog: concurrent run() storm (no deadlock, breaker sane)
- Shape-intern table: concurrent interning across forced rollovers
  (every returned (sid, gen) stays resolvable or detectably stale)
"""

import random
import threading
import time

from karpenter_tpu.runtime.manager import _WorkQueue
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.solver.solve import _DeviceWatchdog

STRESS_SECONDS = 3.0


class TestBatcherRaces:
    def test_concurrent_add_flush_loses_nothing(self):
        b = Batcher(idle_seconds=0.01, max_seconds=0.05, max_items=64)
        produced = []
        consumed = []
        stop = threading.Event()
        errors = []

        def producer(tid):
            try:
                i = 0
                while not stop.is_set():
                    item = (tid, i)
                    b.add(item)
                    produced.append(item)  # list.append is GIL-atomic
                    i += 1
                    if i % 7 == 0:
                        time.sleep(0.001)
            except Exception as e:
                errors.append(repr(e))

        def consumer():
            try:
                while not stop.is_set() or b.added_total > b.consumed_total:
                    items, _ = b.wait()
                    consumed.extend(items)
                    b.flush()
                    if stop.is_set() and not items:
                        return
            except Exception as e:
                errors.append(repr(e))

        threads = [threading.Thread(target=producer, args=(t,)) for t in range(4)]
        ct = threading.Thread(target=consumer)
        for t in threads:
            t.start()
        ct.start()
        time.sleep(STRESS_SECONDS)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        b.stop()  # unblock a consumer parked in wait()
        ct.join(timeout=5.0)
        assert not errors, errors[0]
        # no item lost, none duplicated (consumed may miss the tail cut off
        # by stop() — every CONSUMED item must be unique and produced)
        assert len(consumed) == len(set(consumed))
        assert set(consumed) <= set(produced)
        assert len(produced) - len(consumed) <= b.added_total - b.consumed_total + 64
        # counters are consistent with the item flow
        assert b.consumed_total >= len(consumed)
        assert b.processed_total <= b.consumed_total <= b.added_total


class TestWorkQueueRaces:
    def test_processing_exclusivity_and_no_lost_dirty(self):
        wq = _WorkQueue()
        KEYS = [(f"k{i}", "default") for i in range(8)]
        in_flight = set()
        in_flight_lock = threading.Lock()
        processed = {k: 0 for k in KEYS}
        last_add = {k: 0.0 for k in KEYS}
        last_done = {k: 0.0 for k in KEYS}
        errors = []
        stop = threading.Event()

        def adder():
            rng = random.Random(1)
            while not stop.is_set():
                k = rng.choice(KEYS)
                last_add[k] = time.monotonic()
                wq.add(k)
                time.sleep(rng.uniform(0.0, 0.002))

        def worker():
            try:
                while not stop.is_set():
                    item = wq.get(timeout=0.05)
                    if item is None:
                        continue
                    with in_flight_lock:
                        # client-go contract: a key being processed is never
                        # handed to a second worker
                        assert item not in in_flight, f"{item} handed twice"
                        in_flight.add(item)
                    time.sleep(random.uniform(0.0, 0.002))
                    with in_flight_lock:
                        in_flight.discard(item)
                        processed[item] += 1
                        last_done[item] = time.monotonic()
                    wq.done(item)
            except Exception as e:
                errors.append(repr(e))

        threads = ([threading.Thread(target=adder) for _ in range(3)]
                   + [threading.Thread(target=worker) for _ in range(6)])
        for t in threads:
            t.start()
        time.sleep(STRESS_SECONDS)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        assert not errors, errors[0]
        assert all(processed[k] > 0 for k in KEYS), processed
        # drain: every key added before stop must still be deliverable —
        # dirty re-adds were not lost (process whatever remains)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            item = wq.get(timeout=0.1)
            if item is None:
                break
            wq.done(item)


class TestWatchdogRaces:
    def test_concurrent_run_storm(self):
        wd = _DeviceWatchdog()
        errors = []
        ok = []

        def caller(i):
            try:
                # generous deadline: the single serialized worker queues
                # 24 × ~1 ms jobs; queue-wait has its own equal budget
                r = wd.run(lambda: time.sleep(0.001) or i,
                           timeout_s=5.0, breaker_s=0.2)
                ok.append(r)
            except TimeoutError:
                pass  # acceptable under storm; breaker must stay sane
            except Exception as e:
                errors.append(repr(e))

        threads = [threading.Thread(target=caller, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not any(t.is_alive() for t in threads), "watchdog deadlocked"
        assert not errors, errors[0]
        assert len(ok) >= 20  # the serialized worker drains the storm
        # a subsequent healthy call still works (pool not wedged/leaked)
        assert wd.run(lambda: "after", timeout_s=5.0, breaker_s=0.2) == "after"

    def test_breaker_state_consistent_under_concurrent_trips(self):
        wd = _DeviceWatchdog()
        results = []

        def tripper():
            try:
                wd.run(lambda: time.sleep(2.0), timeout_s=0.05, breaker_s=0.5)
            except TimeoutError:
                results.append("timeout")

        threads = [threading.Thread(target=tripper) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert results, "no trip registered"
        assert wd.tripped()
        time.sleep(0.6)
        assert not wd.tripped()  # breaker closes; no torn _open_until


class TestInternRaces:
    def test_concurrent_interning_across_rollovers(self, monkeypatch):
        from karpenter_tpu.solver import adapter

        monkeypatch.setattr(adapter, "_INTERN_MAX", 64)
        monkeypatch.setattr(adapter, "_VEC_INTERN", {})
        monkeypatch.setattr(adapter, "_VEC_BY_ID", [])
        monkeypatch.setattr(adapter, "_INTERN_GEN", 50_000)
        observed = []  # (vec, sid, gen) triples, appended GIL-atomically
        errors = []

        def worker(seed):
            rng = random.Random(seed)
            try:
                for _ in range(600):
                    vec = (rng.randint(0, 300) * 10**6, 0, 0, 0, 0, 0, 0, 0)
                    sid, gen = adapter._intern_vec(vec)
                    observed.append((vec, sid, gen))
            except Exception as e:
                errors.append(repr(e))

        threads = [threading.Thread(target=worker, args=(s,)) for s in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors, errors[0]
        # every returned (sid, gen) is either resolvable to EXACTLY the
        # interned vec, or detectably stale (snapshot returns None) — a
        # silently-wrong mapping is the race being hunted
        for vec, sid, gen in observed:
            got = adapter.interned_vecs_snapshot([sid], gen)
            assert got is None or got[0] == vec, (
                f"sid {sid}@gen{gen} resolved to {got and got[0]} != {vec}")
        assert len(adapter._VEC_BY_ID) <= 64
