"""Cluster-in-a-box replay harness tests (karpenter_tpu/replay.py).

Fast legs run a shrunken replay (thousands of pods, 2 shards, chaos on)
and a small store A/B — the full million-pod run is ``make bench-replay``
(bench.py config_9). The ``slow`` leg is ``make replay-smoke``: 10k pods
in under a minute with chaos + pressure active.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

import pytest

from karpenter_tpu.replay import (
    ReplayConfig, diurnal_weights, run_replay, store_ab, tenant_catalog,
    tenant_provisioner, tenant_zone,
)
from tools.replay_verdict import verdict

import random


TINY = ReplayConfig(
    pods_total=1_500, shards=2, tenants=2, seed=7, bound_cohort=60,
    churn_pods=120, max_depth=400, ticks=6, tick_sleep_s=0.05,
    burst_ticks=2, chaos=True, settle_s=45.0, flood_pool=64)


class TestDiurnalWeights:
    def test_seeded_and_bursty(self):
        rng = random.Random(42)
        w1 = diurnal_weights(12, 3, random.Random(42))
        w2 = diurnal_weights(12, 3, random.Random(42))
        assert w1 == w2, "diurnal curve must be deterministic per seed"
        assert len(w1) == 12 and all(w > 0 for w in w1)
        # burst ticks carry 3x weight: the top ticks must clearly dominate
        assert max(w1) > 2.0 * (sum(w1) / len(w1))
        assert diurnal_weights(12, 3, rng) != diurnal_weights(12, 0,
                                                              random.Random(1))

    def test_tenant_fixtures(self):
        catalog = tenant_catalog(3)
        zones = {o.zone for it in catalog for o in it.offerings}
        assert zones == {"replay-zone-1", "replay-zone-2", "replay-zone-3"}
        for t in range(3):
            prov = tenant_provisioner(t)
            req = prov.spec.constraints.requirements.requirement(
                "topology.kubernetes.io/zone")
            assert req == {tenant_zone(t)}, \
                "tenant must be pinned to exactly its own zone"


class TestTinyReplay:
    def test_completes_with_zero_critical_sheds(self):
        report = run_replay(TINY)
        assert report["completed"], report
        assert report["system_critical_shed"] == 0
        assert report["cohort_unbound"] == 0
        assert report["workers_healthy"]
        assert report["recovery_to_l0_s"] is not None
        # churn rounding is the only permitted offer shortfall
        assert report["offered_total"] >= 0.99 * TINY.pods_total
        assert set(report["offered"]) >= {"default", "low", "besteffort"}
        # every cohort band got a latency quantile block
        for band, q in report["pending_to_bound_s"].items():
            if q is not None:
                assert q["p99"] >= q["p50"] >= 0.0
        assert report["store_ops"], "store op latency probes missing"
        # the verdict tool must accept the harness's own output shape
        line = {"replay": report, "store_ab": None}
        v = verdict(line)
        assert "PASS" in v and "FAIL" not in v, v

    def test_report_is_json_serializable(self):
        # SLO reports are redirected into BENCH files verbatim
        report = run_replay(ReplayConfig(
            pods_total=400, shards=1, tenants=1, seed=1, bound_cohort=20,
            churn_pods=40, max_depth=200, ticks=4, tick_sleep_s=0.05,
            burst_ticks=1, chaos=False, settle_s=30.0, flood_pool=32))
        text = json.dumps(report)
        assert json.loads(text)["completed"]
        assert json.loads(text)["chaos_fired"] is None  # chaos disabled


class TestSpotReplay:
    def test_spot_cohort_reclaim_rebinds(self):
        """--spot-fraction leg: part of the default-band cohort is pinned
        to spot capacity, the harness's seeded per-tick interruption stream
        reclaims running spot instances mid-run, and ``completed`` proves
        every displaced pod was re-offered and REBOUND. The verdict tool's
        spot cell must accept the report and gate on it."""
        report = run_replay(ReplayConfig(
            pods_total=1_500, shards=2, tenants=2, seed=7, bound_cohort=60,
            churn_pods=120, max_depth=400, ticks=6, tick_sleep_s=0.1,
            burst_ticks=2, chaos=True, settle_s=45.0, flood_pool=64,
            spot_fraction=0.5))
        assert report["completed"], report
        assert report["system_critical_shed"] == 0
        spot = report["spot"]
        assert spot is not None
        assert spot["cohort_spot_pods"] > 0, spot
        # window == draw count: every planned interruption must have fired
        assert spot["interruptions"] >= 1, spot
        assert spot["rebound"] == spot["displaced"], spot
        assert "provider/reclaim/spot-interruption" in report["chaos_fired"]
        v = verdict({"replay": report, "store_ab": None})
        assert "PASS" in v and "FAIL" not in v, v
        assert "spot=" in v

    def test_spot_gates_in_verdict(self):
        base = {
            "config": {"pods_total": 100, "shards": 1, "chaos": True,
                       "spot_fraction": 0.5},
            "offered_total": 100, "completed": True,
            "system_critical_shed": 0, "recovery_to_l0_s": 0.5,
            "peak_level": 1, "pending_to_bound_s": {}}
        ab = {"scan_speedup": 10.0, "objects": 100_000}
        ok = dict(base, spot={"cohort_spot_pods": 10, "interruptions": 2,
                              "instances_reclaimed": 2, "displaced": 4,
                              "rebound": 4, "spot_instances_live": 3})
        assert "PASS" in verdict({"replay": ok, "store_ab": ab})
        stuck = dict(base, spot={"cohort_spot_pods": 10, "interruptions": 2,
                                 "instances_reclaimed": 2, "displaced": 4,
                                 "rebound": 3, "spot_instances_live": 3})
        v = verdict({"replay": stuck, "store_ab": ab})
        assert "FAIL" in v and "never rebound" in v
        vacuous = dict(base, spot={"cohort_spot_pods": 10, "interruptions": 0,
                                   "instances_reclaimed": 0, "displaced": 0,
                                   "rebound": 0, "spot_instances_live": 3})
        v = verdict({"replay": vacuous, "store_ab": ab})
        assert "FAIL" in v and "vacuous" in v


class TestStoreAB:
    def test_small_ab_counts_and_speedup(self):
        ab = store_ab(objects=3_000, minority=300, iters=8)
        assert ab["objects"] == 3_000
        assert ab["minority_kind_objects"] == 300
        assert ab["iters"] == 8
        # even at 3k objects the indexed scan must beat the full filter scan
        assert ab["scan_speedup"] > 1.0
        assert ab["list_speedup"] > 0.0
        assert ab["striped"]["scan_p50_ms"] < ab["naive"]["scan_p50_ms"]


class TestVerdictCli:
    def test_pipe_passthrough_and_pass(self):
        line = json.dumps({
            "replay": {
                "config": {"pods_total": 1000, "shards": 2},
                "offered_total": 995, "completed": True,
                "system_critical_shed": 0, "recovery_to_l0_s": 1.5,
                "peak_level": 2, "pending_to_bound_s": {
                    "default": {"p50": 0.1, "p99": 0.7, "max": 1.0, "n": 10}},
            },
            "store_ab": {"scan_speedup": 33.0, "objects": 100_000},
        })
        proc = subprocess.run(
            [sys.executable, "tools/replay_verdict.py"], input=line + "\n",
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0
        assert proc.stdout.strip() == line, "stdout must pass through unchanged"
        assert "PASS" in proc.stderr

    def test_critical_shed_fails_the_gate(self):
        line = {"replay": {
            "config": {"pods_total": 100, "shards": 1}, "offered_total": 100,
            "completed": True, "system_critical_shed": 3,
            "recovery_to_l0_s": 0.5, "peak_level": 3,
            "pending_to_bound_s": {}}, "store_ab": {"scan_speedup": 10.0}}
        v = verdict(line)
        assert "FAIL" in v and "system-critical" in v

    def test_slow_store_fails_the_gate(self):
        line = {"replay": {
            "config": {"pods_total": 100, "shards": 1}, "offered_total": 100,
            "completed": True, "system_critical_shed": 0,
            "recovery_to_l0_s": 0.5, "peak_level": 1,
            "pending_to_bound_s": {}},
            "store_ab": {"scan_speedup": 2.0, "objects": 100_000}}
        v = verdict(line)
        assert "FAIL" in v and "speedup" in v


class TestReplaySlo:
    def test_clean_leg_zero_trips_bounded_and_parity(self):
        """Default objectives are generous: a fault-free replay must
        produce ZERO burn trips (the pressure ladder shedding flood bands
        is by design, not burn), bounded digest growth, and digest
        quantiles within 1% of the exact per-pod lists. Chaos stays OFF:
        an injected fault can legitimately push the ladder to L3, whose
        objective'd-band sheds ARE burn — that's the probe leg's job."""
        report = run_replay(ReplayConfig(
            pods_total=1_200, shards=1, tenants=1, seed=7, bound_cohort=60,
            churn_pods=60, max_depth=600, ticks=4, tick_sleep_s=0.05,
            burst_ticks=1, chaos=False, settle_s=30.0, flood_pool=32,
            slo_exact_check=True))
        assert report["completed"], report
        s = report["slo"]
        assert s["trips"] == 0, f"clean leg tripped the sentinel: {s}"
        assert s["burning"] == []
        assert s["bounded"], f"digest growth unbounded: {s}"
        assert s["records"] > 0, "engine never stamped a pod"
        parity = report["slo_digest_parity"]
        assert parity["within_1pct"], parity
        # the slo verdict tool must accept the harness's own shape
        from tools.slo_verdict import verdict as slo_verdict
        v = slo_verdict({"replay": report, "slo_chaos": None})
        assert "PASS" in v and "FAIL" not in v, v

    def test_chaos_probe_trips_with_band_and_stage(self):
        """An impossible objective (1ms e2e) is the seeded-chaos stand-in:
        every bound pod breaches, the sentinel must trip, tagged."""
        report = run_replay(ReplayConfig(
            pods_total=800, shards=1, tenants=1, seed=7, bound_cohort=40,
            churn_pods=40, max_depth=400, ticks=3, tick_sleep_s=0.05,
            burst_ticks=1, chaos=True, settle_s=30.0, flood_pool=32,
            slo_objectives={"default": 0.001}))
        assert report["completed"], report
        s = report["slo"]
        assert s["trips"] >= 1, f"sentinel never tripped: {s}"
        assert "default" in s["burning"]
        tag = s["burn"]["last_trip"]
        assert tag["band"] == "default" and tag["stage"] == "e2e"
        assert tag["objective_s"] == 0.001


@pytest.mark.slow
class TestReplaySmoke:
    def test_10k_smoke_under_60s(self):
        """``make replay-smoke``: 10k pods / 2 shards with chaos + pressure,
        wall-clocked — the fast proof that the full 1M run is sane."""
        cfg = ReplayConfig(
            pods_total=10_000, shards=2, tenants=4, seed=42,
            bound_cohort=200, churn_pods=500, max_depth=2_000, ticks=8,
            tick_sleep_s=0.1, burst_ticks=2, chaos=True, settle_s=60.0,
            flood_pool=256, slo_exact_check=True)
        t0 = time.monotonic()
        report = run_replay(cfg)
        wall = time.monotonic() - t0
        print(f"\nreplay-smoke: {report['offered_total']} pods in {wall:.1f}s "
              f"peak=L{report['peak_level']} "
              f"recovery={report['recovery_to_l0_s']}s "
              f"slo={report['slo']['records']}rec "
              f"parity={report['slo_digest_parity']['within_1pct']}")
        assert report["completed"], report
        assert report["system_critical_shed"] == 0
        assert report["offered_total"] >= 0.99 * cfg.pods_total
        assert wall < 60.0, f"smoke took {wall:.1f}s (budget 60s)"
        # at 10k-pod scale the digests must stay bounded, clean, and
        # within 1% of the exact latency lists
        assert report["slo"]["trips"] == 0
        assert report["slo"]["bounded"]
        assert report["slo_digest_parity"]["within_1pct"], \
            report["slo_digest_parity"]
