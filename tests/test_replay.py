"""Cluster-in-a-box replay harness tests (karpenter_tpu/replay.py).

Fast legs run a shrunken replay (thousands of pods, 2 shards, chaos on)
and a small store A/B — the full million-pod run is ``make bench-replay``
(bench.py config_9). The ``slow`` leg is ``make replay-smoke``: 10k pods
in under a minute with chaos + pressure active.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time

import pytest

from karpenter_tpu.replay import (
    ReplayConfig, diurnal_weights, run_replay, store_ab, tenant_catalog,
    tenant_provisioner, tenant_zone,
)
from tools.replay_verdict import verdict

import random


TINY = ReplayConfig(
    pods_total=1_500, shards=2, tenants=2, seed=7, bound_cohort=60,
    churn_pods=120, max_depth=400, ticks=6, tick_sleep_s=0.05,
    burst_ticks=2, chaos=True, settle_s=45.0, flood_pool=64)


class TestDiurnalWeights:
    def test_seeded_and_bursty(self):
        rng = random.Random(42)
        w1 = diurnal_weights(12, 3, random.Random(42))
        w2 = diurnal_weights(12, 3, random.Random(42))
        assert w1 == w2, "diurnal curve must be deterministic per seed"
        assert len(w1) == 12 and all(w > 0 for w in w1)
        # burst ticks carry 3x weight: the top ticks must clearly dominate
        assert max(w1) > 2.0 * (sum(w1) / len(w1))
        assert diurnal_weights(12, 3, rng) != diurnal_weights(12, 0,
                                                              random.Random(1))

    def test_tenant_fixtures(self):
        catalog = tenant_catalog(3)
        zones = {o.zone for it in catalog for o in it.offerings}
        assert zones == {"replay-zone-1", "replay-zone-2", "replay-zone-3"}
        for t in range(3):
            prov = tenant_provisioner(t)
            req = prov.spec.constraints.requirements.requirement(
                "topology.kubernetes.io/zone")
            assert req == {tenant_zone(t)}, \
                "tenant must be pinned to exactly its own zone"


class TestTinyReplay:
    def test_completes_with_zero_critical_sheds(self):
        report = run_replay(TINY)
        assert report["completed"], report
        assert report["system_critical_shed"] == 0
        assert report["cohort_unbound"] == 0
        assert report["workers_healthy"]
        assert report["recovery_to_l0_s"] is not None
        # churn rounding is the only permitted offer shortfall
        assert report["offered_total"] >= 0.99 * TINY.pods_total
        assert set(report["offered"]) >= {"default", "low", "besteffort"}
        # every cohort band got a latency quantile block
        for band, q in report["pending_to_bound_s"].items():
            if q is not None:
                assert q["p99"] >= q["p50"] >= 0.0
        assert report["store_ops"], "store op latency probes missing"
        # the verdict tool must accept the harness's own output shape
        line = {"replay": report, "store_ab": None}
        v = verdict(line)
        assert "PASS" in v and "FAIL" not in v, v

    def test_report_is_json_serializable(self):
        # SLO reports are redirected into BENCH files verbatim
        report = run_replay(ReplayConfig(
            pods_total=400, shards=1, tenants=1, seed=1, bound_cohort=20,
            churn_pods=40, max_depth=200, ticks=4, tick_sleep_s=0.05,
            burst_ticks=1, chaos=False, settle_s=30.0, flood_pool=32))
        text = json.dumps(report)
        assert json.loads(text)["completed"]
        assert json.loads(text)["chaos_fired"] is None  # chaos disabled


class TestStoreAB:
    def test_small_ab_counts_and_speedup(self):
        ab = store_ab(objects=3_000, minority=300, iters=8)
        assert ab["objects"] == 3_000
        assert ab["minority_kind_objects"] == 300
        assert ab["iters"] == 8
        # even at 3k objects the indexed scan must beat the full filter scan
        assert ab["scan_speedup"] > 1.0
        assert ab["list_speedup"] > 0.0
        assert ab["striped"]["scan_p50_ms"] < ab["naive"]["scan_p50_ms"]


class TestVerdictCli:
    def test_pipe_passthrough_and_pass(self):
        line = json.dumps({
            "replay": {
                "config": {"pods_total": 1000, "shards": 2},
                "offered_total": 995, "completed": True,
                "system_critical_shed": 0, "recovery_to_l0_s": 1.5,
                "peak_level": 2, "pending_to_bound_s": {
                    "default": {"p50": 0.1, "p99": 0.7, "max": 1.0, "n": 10}},
            },
            "store_ab": {"scan_speedup": 33.0, "objects": 100_000},
        })
        proc = subprocess.run(
            [sys.executable, "tools/replay_verdict.py"], input=line + "\n",
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0
        assert proc.stdout.strip() == line, "stdout must pass through unchanged"
        assert "PASS" in proc.stderr

    def test_critical_shed_fails_the_gate(self):
        line = {"replay": {
            "config": {"pods_total": 100, "shards": 1}, "offered_total": 100,
            "completed": True, "system_critical_shed": 3,
            "recovery_to_l0_s": 0.5, "peak_level": 3,
            "pending_to_bound_s": {}}, "store_ab": {"scan_speedup": 10.0}}
        v = verdict(line)
        assert "FAIL" in v and "system-critical" in v

    def test_slow_store_fails_the_gate(self):
        line = {"replay": {
            "config": {"pods_total": 100, "shards": 1}, "offered_total": 100,
            "completed": True, "system_critical_shed": 0,
            "recovery_to_l0_s": 0.5, "peak_level": 1,
            "pending_to_bound_s": {}},
            "store_ab": {"scan_speedup": 2.0, "objects": 100_000}}
        v = verdict(line)
        assert "FAIL" in v and "speedup" in v


@pytest.mark.slow
class TestReplaySmoke:
    def test_10k_smoke_under_60s(self):
        """``make replay-smoke``: 10k pods / 2 shards with chaos + pressure,
        wall-clocked — the fast proof that the full 1M run is sane."""
        cfg = ReplayConfig(
            pods_total=10_000, shards=2, tenants=4, seed=42,
            bound_cohort=200, churn_pods=500, max_depth=2_000, ticks=8,
            tick_sleep_s=0.1, burst_ticks=2, chaos=True, settle_s=60.0,
            flood_pool=256)
        t0 = time.monotonic()
        report = run_replay(cfg)
        wall = time.monotonic() - t0
        print(f"\nreplay-smoke: {report['offered_total']} pods in {wall:.1f}s "
              f"peak=L{report['peak_level']} "
              f"recovery={report['recovery_to_l0_s']}s")
        assert report["completed"], report
        assert report["system_critical_shed"] == 0
        assert report["offered_total"] >= 0.99 * cfg.pods_total
        assert wall < 60.0, f"smoke took {wall:.1f}s (budget 60s)"
