"""Requirements algebra + constraints parity with v1alpha5 semantics."""

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints, Limits, Taints
from karpenter_tpu.api.core import (
    Affinity, Container, NodeAffinity, NodeSelectorRequirement as Req,
    NodeSelectorTerm, Pod, PodSpec, PreferredSchedulingTerm, ResourceRequirements,
    Taint, Toleration,
)
from karpenter_tpu.api.requirements import Requirements, pod_requirements
from karpenter_tpu.utils.resources import parse_resource_list


def make_pod(node_selector=None, tolerations=None, requests=None, preferred=None, required=None):
    affinity = None
    if preferred or required:
        affinity = Affinity(node_affinity=NodeAffinity(
            required=required,
            preferred=preferred or [],
        ))
    return Pod(spec=PodSpec(
        node_selector=node_selector or {},
        tolerations=tolerations or [],
        affinity=affinity,
        containers=[Container(resources=ResourceRequirements.make(requests=requests or {"cpu": "1"}))],
    ))


class TestRequirements:
    def test_in_intersection(self):
        r = Requirements().add(
            Req(key="k", operator="In", values=["a", "b"]),
            Req(key="k", operator="In", values=["b", "c"]),
        )
        assert r.requirement("k") == {"b"}

    def test_notin_difference(self):
        r = Requirements().add(
            Req(key="k", operator="In", values=["a", "b", "c"]),
            Req(key="k", operator="NotIn", values=["b"]),
        )
        assert r.requirement("k") == {"a", "c"}

    def test_unconstrained_is_none(self):
        assert Requirements().requirement("missing") is None

    def test_normalize_aliases(self):
        r = Requirements().add(Req(key="beta.kubernetes.io/arch", operator="In", values=["amd64"]))
        assert r.architectures() == {"amd64"}

    def test_consolidate(self):
        r = Requirements().add(
            Req(key="k", operator="In", values=["a", "b"]),
            Req(key="k", operator="NotIn", values=["a"]),
        ).consolidate()
        assert len(r.items) == 1
        assert r.items[0].operator == "In"
        assert set(r.items[0].values) == {"b"}

    def test_well_known_filters(self):
        r = Requirements().add(
            Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In", values=["z1"]),
            Req(key="custom/label", operator="In", values=["v"]),
        ).well_known()
        assert r.keys() == [wellknown.LABEL_TOPOLOGY_ZONE]

    def test_pod_requirements_heaviest_preferred_and_first_required(self):
        pod = make_pod(
            node_selector={"ns": "v"},
            preferred=[
                PreferredSchedulingTerm(weight=1, preference=NodeSelectorTerm(
                    match_expressions=[Req(key="light", operator="In", values=["x"])])),
                PreferredSchedulingTerm(weight=10, preference=NodeSelectorTerm(
                    match_expressions=[Req(key="heavy", operator="In", values=["y"])])),
            ],
            required=[
                NodeSelectorTerm(match_expressions=[Req(key="req1", operator="In", values=["a"])]),
                NodeSelectorTerm(match_expressions=[Req(key="req2", operator="In", values=["b"])]),
            ],
        )
        r = pod_requirements(pod)
        keys = set(r.keys())
        assert "ns" in keys and "heavy" in keys and "req1" in keys
        assert "light" not in keys and "req2" not in keys


class TestTaints:
    def test_tolerates(self):
        ts = Taints([Taint(key="team", value="a", effect="NoSchedule")])
        ok = make_pod(tolerations=[Toleration(key="team", operator="Equal", value="a", effect="NoSchedule")])
        bad = make_pod()
        assert ts.tolerates(ok) == []
        assert ts.tolerates(bad) != []

    def test_exists_toleration(self):
        ts = Taints([Taint(key="team", value="a", effect="NoSchedule")])
        pod = make_pod(tolerations=[Toleration(key="team", operator="Exists")])
        assert ts.tolerates(pod) == []

    def test_with_pod_generates_both_effects(self):
        ts = Taints().with_pod(make_pod(tolerations=[Toleration(key="k", operator="Equal", value="v")]))
        assert len(ts) == 2
        assert {t.effect for t in ts} == {"NoSchedule", "NoExecute"}

    def test_with_pod_ignores_exists(self):
        ts = Taints().with_pod(make_pod(tolerations=[Toleration(key="k", operator="Exists")]))
        assert len(ts) == 0


class TestConstraints:
    def make_constraints(self):
        return Constraints(requirements=Requirements().add(
            Req(key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In", values=["z1", "z2"]),
            Req(key=wellknown.LABEL_ARCH, operator="In", values=["amd64"]),
        ))

    def test_validate_pod_ok(self):
        c = self.make_constraints()
        assert c.validate_pod(make_pod(node_selector={wellknown.LABEL_TOPOLOGY_ZONE: "z1"})) is None

    def test_validate_pod_unknown_key(self):
        c = self.make_constraints()
        assert c.validate_pod(make_pod(node_selector={"unknown": "v"})) is not None

    def test_validate_pod_incompatible_value(self):
        c = self.make_constraints()
        assert c.validate_pod(make_pod(node_selector={wellknown.LABEL_TOPOLOGY_ZONE: "z9"})) is not None

    def test_validate_pod_taints(self):
        c = self.make_constraints()
        c.taints = Taints([Taint(key="t", value="v", effect="NoSchedule")])
        assert c.validate_pod(make_pod()) is not None

    def test_tighten(self):
        c = self.make_constraints()
        t = c.tighten(make_pod(node_selector={wellknown.LABEL_TOPOLOGY_ZONE: "z1", "custom": "x"}))
        assert t.requirements.zones() == {"z1"}
        # non-well-known keys are dropped
        assert t.requirements.requirement("custom") is None


class TestLimits:
    def test_no_limits(self):
        assert Limits().exceeded_by(parse_resource_list({"cpu": "100"})) is None

    def test_exceeded(self):
        l = Limits(resources=parse_resource_list({"cpu": "10"}))
        assert l.exceeded_by(parse_resource_list({"cpu": "10"})) is not None
        assert l.exceeded_by(parse_resource_list({"cpu": "9"})) is None
