"""Quantity parsing/arithmetic parity with k8s resource.Quantity."""

from karpenter_tpu.utils.resources import (
    Quantity, merge, parse_resource_list, requests_for_pods,
)
from karpenter_tpu.api.core import Container, Pod, PodSpec, ResourceRequirements


def q(s):
    return Quantity.parse(s)


def test_parse_milli():
    assert q("100m").milli_value() == 100
    assert q("1").milli_value() == 1000
    assert q("1.5").milli_value() == 1500
    assert q("2500m").value() == 3  # rounds up like k8s Value()


def test_parse_binary():
    assert q("1Ki").value() == 1024
    assert q("512Mi").value() == 512 * 1024**2
    assert q("2Gi").value() == 2 * 1024**3
    assert q("1.5Gi").value() == 3 * 1024**3 // 2


def test_parse_decimal_suffix():
    assert q("1k").value() == 1000
    assert q("2G").value() == 2 * 10**9
    assert q("1e3").value() == 1000


def test_cmp_add():
    assert q("1").cmp(q("1000m")) == 0
    assert q("1100m").cmp(q("1")) == 1
    assert q("900m").cmp(q("1")) == -1
    assert q("1").add(q("500m")).milli_value() == 1500
    assert q("0").is_zero()


def test_ordering_hash():
    assert q("1") == q("1000m")
    assert hash(q("1")) == hash(q("1000m"))
    assert q("1") < q("2")
    assert sorted([q("3"), q("1"), q("2")]) == [q("1"), q("2"), q("3")]


def test_merge():
    a = parse_resource_list({"cpu": "1", "memory": "1Gi"})
    b = parse_resource_list({"cpu": "500m", "pods": "1"})
    m = merge(a, b)
    assert m["cpu"].milli_value() == 1500
    assert m["memory"].value() == 1024**3
    assert m["pods"].value() == 1


def test_requests_for_pods():
    pod = Pod(spec=PodSpec(containers=[
        Container(resources=ResourceRequirements.make(requests={"cpu": "1", "memory": "1Gi"})),
        Container(resources=ResourceRequirements.make(requests={"cpu": "250m"})),
    ]))
    r = requests_for_pods(pod)
    assert r["cpu"].milli_value() == 1250
    assert r["memory"].value() == 1024**3
