"""Scheduling suite: combined constraints, well-known labels, preferential
fallback, taints — mirrors pkg/controllers/provisioning/scheduling/
suite_test.go (sections at lines 81 Combined Constraints / 314 Preferential
Fallback / 641 Taints; the Topology section lives in tests/test_topology.py).
"""

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.constraints import Constraints, Taints
from karpenter_tpu.api.core import (
    Affinity, NodeAffinity, NodeSelectorRequirement as Req, NodeSelectorTerm,
    PreferredSchedulingTerm, Taint, Toleration,
)
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.scheduling.batcher import Batcher

from tests.expectations import (
    expect_not_scheduled, expect_provisioned, expect_scheduled,
    make_provisioner, unschedulable_pod,
)

ZONE = wellknown.LABEL_TOPOLOGY_ZONE


@pytest.fixture()
def env():
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=instance_types(10))
    provisioning = ProvisioningController(
        kube, provider,
        batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
    selection = SelectionController(kube, provisioning, gate_timeout=30.0)
    yield kube, provider, provisioning, selection
    for w in provisioning.workers.values():
        w.stop()


def setup_provisioner(kube, provisioning, **spec_kwargs):
    provisioner = make_provisioner(**spec_kwargs)
    kube.create(provisioner)
    provisioning.reconcile(provisioner.metadata.name)
    return provisioner


def required_affinity(*terms):
    return Affinity(node_affinity=NodeAffinity(
        required=[NodeSelectorTerm(match_expressions=list(t)) for t in terms]))


def preferred_affinity(*weighted_terms):
    return Affinity(node_affinity=NodeAffinity(preferred=[
        PreferredSchedulingTerm(
            weight=w, preference=NodeSelectorTerm(match_expressions=list(t)))
        for w, t in weighted_terms
    ]))


def node_of(kube, pod):
    return kube.get("Node", expect_scheduled(kube, pod), "")


class TestCombinedConstraintsCustomLabels:
    """suite_test.go:82-133."""

    def test_unconstrained_pod_schedules_despite_provisioner_labels(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            labels={"test-key": "test-value"}))
        pods = [unschedulable_pod()]
        expect_provisioned(kube, selection, provisioning, pods)
        node = node_of(kube, pods[0])
        assert node.metadata.labels["test-key"] == "test-value"

    def test_conflicting_node_selector_not_scheduled(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            labels={"test-key": "test-value"}))
        # labels are NOT requirements (constraints.go:46-56): an unknown
        # selector key has an empty requirement set and is rejected
        pod = unschedulable_pod(node_selector={"test-key": "different-value"})
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_matching_custom_requirement_schedules(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key="test-key", operator="In",
                                           values=["test-value"])])))
        pods = [unschedulable_pod(node_selector={"test-key": "test-value"})]
        expect_provisioned(kube, selection, provisioning, pods)
        expect_scheduled(kube, pods[0])

    def test_conflicting_custom_requirement_not_scheduled(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key="test-key", operator="In",
                                           values=["test-value"])])))
        pod = unschedulable_pod(node_selector={"test-key": "different-value"})
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_matching_preference_schedules(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key="test-key", operator="In",
                                           values=["test-value"])])))
        pods = [unschedulable_pod(affinity=preferred_affinity(
            (1, [Req(key="test-key", operator="In", values=["test-value"])])))]
        expect_provisioned(kube, selection, provisioning, pods)
        expect_scheduled(kube, pods[0])

    def test_conflicting_preference_not_scheduled_first_pass(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key="test-key", operator="In",
                                           values=["test-value"])])))
        pod = unschedulable_pod(affinity=preferred_affinity(
            (1, [Req(key="test-key", operator="NotIn", values=["test-value"])])))
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)


class TestWellKnownLabels:
    """suite_test.go:135-312."""

    def test_provisioner_zone_constraint_flows_to_node(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key=ZONE, operator="In",
                                           values=["test-zone-2"])])))
        pods = [unschedulable_pod()]
        expect_provisioned(kube, selection, provisioning, pods)
        assert node_of(kube, pods[0]).metadata.labels[ZONE] == "test-zone-2"

    def test_node_selector_outside_provisioner_constraint_rejected(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key=ZONE, operator="In",
                                           values=["test-zone-1"])])))
        pod = unschedulable_pod(node_selector={ZONE: "test-zone-2"})
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_unknown_node_selector_value_rejected(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod(node_selector={ZONE: "no-such-zone"})
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_compatible_required_affinity_in(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod(affinity=required_affinity(
            [Req(key=ZONE, operator="In", values=["test-zone-3"])]))]
        expect_provisioned(kube, selection, provisioning, pods)
        assert node_of(kube, pods[0]).metadata.labels[ZONE] == "test-zone-3"

    def test_compatible_required_affinity_notin(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod(affinity=required_affinity(
            [Req(key=ZONE, operator="NotIn",
                 values=["test-zone-1", "test-zone-2"])]))]
        expect_provisioned(kube, selection, provisioning, pods)
        assert node_of(kube, pods[0]).metadata.labels[ZONE] == "test-zone-3"

    def test_incompatible_required_affinity_in(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key=ZONE, operator="In",
                                           values=["test-zone-1"])])))
        pod = unschedulable_pod(affinity=required_affinity(
            [Req(key=ZONE, operator="In", values=["test-zone-2"])]))
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_incompatible_notin_strips_all_zones(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([Req(key=ZONE, operator="In",
                                           values=["test-zone-1"])])))
        pod = unschedulable_pod(affinity=required_affinity(
            [Req(key=ZONE, operator="NotIn", values=["test-zone-1"])]))
        kube.create(pod)
        selection.reconcile(pod.metadata.name)
        expect_not_scheduled(kube, pod)

    def test_multidimensional_selector_preference_requirement(self, env):
        """suite_test.go:271-291: selectors + preferences + requirements all
        intersect; the surviving cell wins."""
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning, constraints=Constraints(
            requirements=Requirements([
                Req(key=ZONE, operator="In",
                    values=["test-zone-1", "test-zone-2", "test-zone-3"]),
            ])))
        affinity = preferred_affinity(
            (1, [Req(key=ZONE, operator="NotIn", values=["test-zone-1"])]))
        affinity.node_affinity.required = [NodeSelectorTerm(match_expressions=[
            Req(key=ZONE, operator="In", values=["test-zone-2", "test-zone-3"]),
        ])]
        pods = [unschedulable_pod(
            node_selector={ZONE: "test-zone-3"}, affinity=affinity)]
        expect_provisioned(kube, selection, provisioning, pods)
        assert node_of(kube, pods[0]).metadata.labels[ZONE] == "test-zone-3"

    def test_beta_zone_label_alias_normalized(self, env):
        """NormalizedLabels (requirements.go:65-70): the beta alias maps to
        the GA topology key."""
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod(
            node_selector={"failure-domain.beta.kubernetes.io/zone": "test-zone-2"})]
        expect_provisioned(kube, selection, provisioning, pods)
        assert node_of(kube, pods[0]).metadata.labels[ZONE] == "test-zone-2"


class TestPreferentialFallback:
    """suite_test.go:314-417: relaxation across retries (preferences.go)."""

    def reconcile_until_scheduled(self, kube, selection, pod, attempts=5):
        for _ in range(attempts):
            selection.reconcile(pod.metadata.name)
            stored = kube.get("Pod", pod.metadata.name)
            if stored.spec.node_name:
                return stored
        return kube.get("Pod", pod.metadata.name)

    def test_never_relaxes_the_final_required_term(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod(affinity=required_affinity(
            [Req(key=ZONE, operator="In", values=["invalid-zone"])]))
        kube.create(pod)
        stored = self.reconcile_until_scheduled(kube, selection, pod, attempts=4)
        assert not stored.spec.node_name

    def test_relaxes_required_or_terms_until_valid(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod(affinity=required_affinity(
            [Req(key=ZONE, operator="In", values=["invalid-a"])],
            [Req(key=ZONE, operator="In", values=["invalid-b"])],
            [Req(key=ZONE, operator="In", values=["test-zone-1"])],
        ))
        kube.create(pod)
        stored = self.reconcile_until_scheduled(kube, selection, pod)
        assert stored.spec.node_name
        node = kube.get("Node", stored.spec.node_name, "")
        assert node.metadata.labels[ZONE] == "test-zone-1"

    def test_relaxes_preferred_terms_heaviest_first(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod(affinity=preferred_affinity(
            (1, [Req(key=ZONE, operator="In", values=["test-zone-1"])]),
            (100, [Req(key=ZONE, operator="In", values=["invalid-zone"])]),
        ))
        kube.create(pod)
        stored = self.reconcile_until_scheduled(kube, selection, pod)
        assert stored.spec.node_name
        node = kube.get("Node", stored.spec.node_name, "")
        # the invalid weight-100 term was stripped; weight-1 then applied
        assert node.metadata.labels[ZONE] == "test-zone-1"

    def test_relaxes_all_preferred_terms_to_unconstrained(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pod = unschedulable_pod(affinity=preferred_affinity(
            (2, [Req(key=ZONE, operator="In", values=["invalid-a"])]),
            (1, [Req(key=ZONE, operator="In", values=["invalid-b"])]),
        ))
        kube.create(pod)
        stored = self.reconcile_until_scheduled(kube, selection, pod)
        assert stored.spec.node_name


class TestTaints:
    """suite_test.go:641-686."""

    def test_nodes_carry_provisioner_taints(self, env):
        kube, provider, provisioning, selection = env
        taint = Taint(key="test", value="bar", effect="NoSchedule")
        setup_provisioner(kube, provisioning,
                          constraints=Constraints(taints=Taints([taint])))
        pods = [unschedulable_pod(tolerations=[
            Toleration(operator="Exists", effect="NoSchedule")])]
        expect_provisioned(kube, selection, provisioning, pods)
        node = node_of(kube, pods[0])
        assert any(t.key == "test" and t.value == "bar" and
                   t.effect == "NoSchedule" for t in node.spec.taints)

    def test_toleration_matrix(self, env):
        kube, provider, provisioning, selection = env
        taint = Taint(key="test-key", value="test-value", effect="NoSchedule")
        setup_provisioner(kube, provisioning,
                          constraints=Constraints(taints=Taints([taint])))
        schedulable = [
            unschedulable_pod(tolerations=[Toleration(
                key="test-key", operator="Exists", effect="NoSchedule")]),
            unschedulable_pod(tolerations=[Toleration(
                key="test-key", operator="Equal", value="test-value",
                effect="NoSchedule")]),
        ]
        expect_provisioned(kube, selection, provisioning, schedulable)
        for p in schedulable:
            expect_scheduled(kube, p)
        unschedulable = [
            unschedulable_pod(),  # missing toleration
            unschedulable_pod(tolerations=[Toleration(
                key="invalid", operator="Exists")]),  # key mismatch
            unschedulable_pod(tolerations=[Toleration(
                key="test-key", operator="Equal", effect="NoSchedule")]),  # value mismatch
        ]
        for p in unschedulable:
            kube.create(p)
            selection.reconcile(p.metadata.name)
            expect_not_scheduled(kube, p)

    def test_opexists_toleration_generates_no_taints(self, env):
        kube, provider, provisioning, selection = env
        setup_provisioner(kube, provisioning)
        pods = [unschedulable_pod(tolerations=[Toleration(
            key="test-key", operator="Exists", effect="NoExecute")])]
        expect_provisioned(kube, selection, provisioning, pods)
        node = node_of(kube, pods[0])
        # only the not-ready startup taint — nothing generated from OpExists
        assert [t.key for t in node.spec.taints] == [wellknown.NOT_READY_TAINT_KEY]

    def test_with_pod_generates_taints_for_equal_tolerations(self):
        """Taints.with_pod semantics (taints.go:27-53) — behavior the
        reference skips wiring into scheduling but keeps in the API."""
        base = Taints([Taint(key="existing", value="v", effect="NoSchedule")])
        pod = unschedulable_pod(tolerations=[
            Toleration(key="a", operator="Equal", value="1", effect="NoSchedule"),
            Toleration(key="b", operator="Equal", value="2"),  # all effects
            Toleration(key="c", operator="Exists"),            # ignored
            Toleration(key="existing", operator="Equal", value="v",
                       effect="NoSchedule"),                   # deduped
        ])
        out = base.with_pod(pod)
        got = {(t.key, t.value, t.effect) for t in out}
        assert got == {
            ("existing", "v", "NoSchedule"),
            ("a", "1", "NoSchedule"),
            ("b", "2", "NoSchedule"),
            ("b", "2", "NoExecute"),
        }


class TestRelaxationTTL:
    """preferences.go:40-48: the original affinity is cached for 5 minutes;
    after expiry a retry starts again from the ORIGINAL (un-relaxed) terms."""

    def test_cache_expiry_restores_original_preferences(self, env):
        from karpenter_tpu.controllers.selection import (
            RELAXATION_TTL_SECONDS, Preferences,
        )
        from karpenter_tpu.utils import clock

        clock.DEFAULT.set(2_000_000.0)
        try:
            prefs = Preferences()
            pod = unschedulable_pod(affinity=preferred_affinity(
                (5, [Req(key=ZONE, operator="In", values=["invalid"])]),
                (1, [Req(key=ZONE, operator="In", values=["test-zone-1"])]),
            ))
            prefs.relax(pod)   # caches original
            prefs.relax(pod)   # strips the heaviest (invalid) term
            assert len(pod.spec.affinity.node_affinity.preferred) == 1

            clock.DEFAULT.advance(RELAXATION_TTL_SECONDS + 1)
            fresh = unschedulable_pod(affinity=preferred_affinity(
                (5, [Req(key=ZONE, operator="In", values=["invalid"])]),
                (1, [Req(key=ZONE, operator="In", values=["test-zone-1"])]),
            ))
            fresh.metadata.uid = pod.metadata.uid
            prefs.relax(fresh)  # expired: treated as first-seen again
            assert len(fresh.spec.affinity.node_affinity.preferred) == 2
        finally:
            clock.DEFAULT.reset()


class TestWindowLogAggregation:
    """Scheduler._get_schedules logs one summary line per window instead of
    one line per unschedulable pod (50k-pod windows must not pay O(N) log
    I/O)."""

    def test_single_summary_line_with_sample_reasons(self, caplog):
        import logging

        from karpenter_tpu.scheduling.scheduler import Scheduler

        constraints = Constraints(requirements=Requirements().add(
            Req(key=ZONE, operator="In", values=["test-zone-1"])))
        pods = [unschedulable_pod(node_selector={ZONE: "test-zone-1"},
                                  name="ok-1")]
        for i in range(8):
            pods.append(unschedulable_pod(
                node_selector={ZONE: f"nope-{i}"}, name=f"bad-{i}"))
        with caplog.at_level(logging.INFO, logger="karpenter.scheduler"):
            schedules = Scheduler(KubeCore())._get_schedules(constraints, pods)
        assert len(schedules) == 1 and len(schedules[0].pods) == 1
        records = [r for r in caplog.records
                   if "unable to schedule" in r.getMessage()]
        assert len(records) == 1
        message = records[0].getMessage()
        assert "8/9" in message
        # at most 5 sample reasons, each naming a pod and the scalar error
        assert message.count("invalid nodeSelector") == 5
        assert "default/bad-0" in message

    def test_no_line_when_everything_schedules(self, caplog):
        import logging

        from karpenter_tpu.scheduling.scheduler import Scheduler

        constraints = Constraints(requirements=Requirements().add(
            Req(key=ZONE, operator="In", values=["test-zone-1"])))
        pods = [unschedulable_pod(name=f"p-{i}") for i in range(3)]
        with caplog.at_level(logging.INFO, logger="karpenter.scheduler"):
            Scheduler(KubeCore())._get_schedules(constraints, pods)
        assert not [r for r in caplog.records
                    if "unable to schedule" in r.getMessage()]

    def test_topology_reason_bucket(self, caplog):
        """Pods Topology.inject marked ``_topology_unsat`` (no satisfiable
        spread domain) are bucketed separately in the window summary."""
        import logging

        from karpenter_tpu.scheduling.scheduler import Scheduler

        constraints = Constraints(requirements=Requirements().add(
            Req(key=ZONE, operator="In", values=["test-zone-1"])))
        pods = [unschedulable_pod(node_selector={ZONE: "test-zone-1"},
                                  name="ok-1")]
        for i in range(3):
            # what inject leaves behind for an unsatisfiable spread: the ""
            # domain selector plus the marker
            p = unschedulable_pod(node_selector={ZONE: ""}, name=f"topo-{i}")
            p.__dict__["_topology_unsat"] = True
            pods.append(p)
        for i in range(2):
            pods.append(unschedulable_pod(
                node_selector={ZONE: f"nope-{i}"}, name=f"bad-{i}"))
        with caplog.at_level(logging.INFO, logger="karpenter.scheduler"):
            schedules = Scheduler(KubeCore())._get_schedules(constraints, pods)
        assert len(schedules) == 1 and len(schedules[0].pods) == 1
        records = [r for r in caplog.records
                   if "unable to schedule" in r.getMessage()]
        assert len(records) == 1
        message = records[0].getMessage()
        assert "5/6" in message
        assert "reason=topology: 3" in message
        assert "other: 2" in message


class TestMemoizedTighten:
    """The scheduler memoizes constraints.tighten() per group signature;
    the memoized result must be structurally identical to tightening every
    pod individually (the pre-columnar behavior)."""

    def test_memoized_equals_per_pod(self):
        from karpenter_tpu.ops import feasibility
        from karpenter_tpu.scheduling.scheduler import (
            Scheduler, _constraints_key,
        )
        from karpenter_tpu.utils import resources as res

        constraints = Constraints(
            labels={"team": "infra"},
            requirements=Requirements().add(
                Req(key=ZONE, operator="In",
                    values=["test-zone-1", "test-zone-2"])))
        pods = [unschedulable_pod(node_selector={ZONE: "test-zone-1"},
                                  name=f"p-{i}") for i in range(6)]
        pods += [unschedulable_pod(node_selector={ZONE: "test-zone-2"},
                                   name=f"q-{i}") for i in range(6)]
        schedules = Scheduler(KubeCore())._get_schedules(constraints, pods)
        assert len(schedules) == 2
        assert sorted(len(s.pods) for s in schedules) == [6, 6]
        for s in schedules:
            for pod in s.pods:
                per_pod = constraints.tighten(pod)
                assert (_constraints_key(per_pod, res.gpu_limits_for(pod))
                        == _constraints_key(s.constraints,
                                            res.gpu_limits_for(pod)))
                assert (feasibility.constraints_key_parts(per_pod)
                        == feasibility.constraints_key_parts(s.constraints))
                assert per_pod.labels == s.constraints.labels
                assert list(per_pod.taints) == list(s.constraints.taints)
