"""Sharded batch solve on a virtual 8-device CPU mesh."""

import numpy as np

from tests.conftest import cpu_mesh_devices
from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.ops.encode import encode
from karpenter_tpu.parallel.mesh import solver_mesh
from karpenter_tpu.parallel.sharded_pack import (
    pack_batch_sharded, pack_batch_sharded_flat, pad_problems, unpack_batch_flat,
)
from karpenter_tpu.solver import host_ffd
from karpenter_tpu.solver.adapter import build_packables, pod_vector
from tests.test_pack_parity import allow_all_constraints, make_pod


def encode_problem(n_pods, cpu_m, n_types):
    pods = [make_pod({"cpu": f"{cpu_m}m", "memory": "256Mi"}) for _ in range(n_pods)]
    catalog = instance_types(n_types)
    constraints = allow_all_constraints(catalog)
    packables, _ = build_packables(catalog, constraints, pods, [])
    vecs = [pod_vector(p) for p in pods]
    ids = list(range(len(pods)))
    order = sorted(range(len(ids)), key=lambda i: tuple(-v for v in vecs[i]))
    enc = encode([vecs[i] for i in order], [ids[i] for i in order], packables)
    assert enc is not None
    return enc, vecs, ids, packables


def test_batch_sharded_matches_host():
    mesh = solver_mesh(cpu_mesh_devices(8))
    problems, hosts = [], []
    for b in range(8):
        enc, vecs, ids, packables = encode_problem(
            n_pods=20 + 13 * b, cpu_m=250 + 250 * (b % 3), n_types=4 + b)
        problems.append(enc)
        hosts.append(host_ffd.pack(vecs, ids, packables))

    shapes, counts, dropped, totals, reserved0, valid, last_valid, pods_unit, B = (
        pad_problems(problems, mesh.devices.size))
    out = pack_batch_sharded(
        shapes, counts, dropped, totals, reserved0, valid, last_valid, pods_unit,
        num_iters=64, mesh=mesh)
    counts_f, dropped_f, done_f, chosen_seq, q_seq, packed_seq = map(np.asarray, out)

    assert done_f.all()
    for b in range(B):
        node_count = int(q_seq[b][q_seq[b] > 0].sum())
        assert node_count == hosts[b].node_count, f"problem {b}"

    # the single-fetch flat variant must agree component-for-component
    buf = np.asarray(pack_batch_sharded_flat(
        shapes, counts, dropped, totals, reserved0, valid, last_valid, pods_unit,
        num_iters=64, mesh=mesh))
    fc, fd, fdone, fchosen, fq, fpacked = unpack_batch_flat(
        buf, shapes.shape[1], 64)
    np.testing.assert_array_equal(fc, counts_f)
    np.testing.assert_array_equal(fd, dropped_f)
    np.testing.assert_array_equal(fdone, done_f)
    np.testing.assert_array_equal(fchosen, np.asarray(chosen_seq))
    np.testing.assert_array_equal(fq, np.asarray(q_seq))
    np.testing.assert_array_equal(fpacked, np.asarray(packed_seq))
