"""Sharded control plane: shard routing, worker lifecycle, per-shard
metric labels, and the jittered requeue backoff (docs/scale.md §1).

``ProvisioningController(shards=N)`` replaces one-worker-per-Provisioner
with N long-lived shard workers keyed by ``crc32(name) % N``; tenants
attach/detach ENGINES while the worker (thread, batcher, queued pods)
survives. The legacy ``shards=0`` shape must be byte-for-byte preserved.
"""

from __future__ import annotations

import random
import string
import zlib

import pytest

from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import (
    ProvisioningController, shard_of,
)
from karpenter_tpu.controllers.selection import (
    JITTER_SPREAD, SelectionController, requeue_jitter,
)
from karpenter_tpu.metrics.pressure import INTAKE_QUEUE_DEPTH, PODS_SHED_TOTAL
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.scheduling.batcher import Batcher

from tests.expectations import (
    expect_provisioned, expect_scheduled, make_provisioner, unschedulable_pod,
)


@pytest.fixture()
def sharded_env():
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=instance_types(10))
    provisioning = ProvisioningController(
        kube, provider, shards=2,
        batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
    selection = SelectionController(kube, provisioning, gate_timeout=30.0)
    yield kube, provider, provisioning, selection
    for w in provisioning.workers.values():
        w.stop()


def _reconcile_cr(kube, provisioning, name):
    p = make_provisioner(name=name)
    kube.create(p)
    provisioning.reconcile(name)
    return p


class TestShardOf:
    def test_stable_and_in_range(self):
        rng = random.Random(42)
        for _ in range(200):
            name = "".join(rng.choices(string.ascii_lowercase + "-", k=12))
            for shards in (1, 2, 4, 7):
                s = shard_of(name, shards)
                assert 0 <= s < shards
                assert s == shard_of(name, shards), "unstable assignment"
                assert s == zlib.crc32(name.encode()) % shards

    def test_spreads_tenants(self):
        # 64 tenants over 4 shards: every shard gets someone (a pathological
        # hash would silently serialize the whole control plane)
        counts = [0] * 4
        for i in range(64):
            counts[shard_of(f"tenant-{i}", 4)] += 1
        assert all(c > 0 for c in counts), counts


class TestShardedController:
    def test_engines_route_by_hash_and_workers_are_shared(self, sharded_env):
        kube, _, provisioning, _ = sharded_env
        names = [f"tenant-{i}" for i in range(6)]
        for n in names:
            _reconcile_cr(kube, provisioning, n)
        assert set(provisioning.workers) <= {"shard-0", "shard-1"}
        hosted = {}
        for wname, worker in provisioning.workers.items():
            sid = wname.split("-", 1)[1]
            assert worker.shard == sid
            assert worker.batcher.shard == sid  # metric label plumbed through
            for eng in worker.engines():
                assert eng.shard == sid
                hosted[eng.provisioner.metadata.name] = int(sid)
        assert hosted == {n: shard_of(n, 2) for n in names}
        # targets() exposes every (provisioner, worker) routing pair
        pairs = provisioning.targets()
        assert sorted(p.metadata.name for p, _ in pairs) == sorted(names)
        for prov, worker in pairs:
            assert worker is provisioning.workers[
                f"shard-{shard_of(prov.metadata.name, 2)}"]

    def test_cr_delete_detaches_engine_but_worker_survives(self, sharded_env):
        kube, _, provisioning, _ = sharded_env
        names = [f"tenant-{i}" for i in range(4)]
        for n in names:
            _reconcile_cr(kube, provisioning, n)
        victim = names[0]
        sid = shard_of(victim, 2)
        worker = provisioning.workers[f"shard-{sid}"]
        before = {e.provisioner.metadata.name for e in worker.engines()}
        assert victim in before
        kube.delete("Provisioner", victim, "default")
        assert provisioning.reconcile(victim) is None
        after = {e.provisioner.metadata.name for e in worker.engines()}
        assert after == before - {victim}
        assert f"shard-{sid}" in provisioning.workers, "shard worker torn down"
        assert worker._thread is not None and worker._thread.is_alive()
        assert victim not in {p.metadata.name for p, _ in provisioning.targets()}

    def test_spec_change_replaces_engine_in_place(self, sharded_env):
        kube, _, provisioning, _ = sharded_env
        _reconcile_cr(kube, provisioning, "tenant-0")
        worker = provisioning.workers[f"shard-{shard_of('tenant-0', 2)}"]
        old_engine = worker.engines()[0]
        old_batcher = worker.batcher

        def bump(p):
            p.spec.constraints.labels["generation"] = "2"
        kube.patch("Provisioner", "tenant-0", "default", bump)
        provisioning.reconcile("tenant-0")
        new_engine = worker.engines()[0]
        assert new_engine is not old_engine, "spec change did not re-attach"
        assert worker.batcher is old_batcher, "intake queue was not preserved"

    def test_end_to_end_bind_through_shard_workers(self, sharded_env):
        kube, provider, provisioning, selection = sharded_env
        _reconcile_cr(kube, provisioning, "default")
        pods = [unschedulable_pod() for _ in range(5)]
        expect_provisioned(kube, selection, provisioning, pods)
        for pod in pods:
            expect_scheduled(kube, pod)
        assert len(provider.created) >= 1

    def test_legacy_unsharded_shape_preserved(self):
        kube = KubeCore()
        provider = FakeCloudProvider(catalog=instance_types(4))
        provisioning = ProvisioningController(
            kube, provider,
            batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
        try:
            for n in ("alpha", "beta"):
                _reconcile_cr(kube, provisioning, n)
            assert set(provisioning.workers) == {"alpha", "beta"}
            for name, worker in provisioning.workers.items():
                assert worker.shard == ""
                assert worker.batcher.shard == ""  # legacy unlabeled series
                assert [e.provisioner.metadata.name
                        for e in worker.engines()] == [name]
            kube.delete("Provisioner", "alpha", "default")
            provisioning.reconcile("alpha")
            assert set(provisioning.workers) == {"beta"}, \
                "legacy shape must tear the worker down with its CR"
        finally:
            for w in provisioning.workers.values():
                w.stop()


class TestPerShardMetrics:
    def test_shed_counter_carries_shard_label(self):
        b = Batcher(idle_seconds=0.05, max_seconds=0.5, max_depth=1)
        b.shard = "97"  # unique value: the registry is process-global
        assert b.add("first", band="default") is not None
        assert b.add("second", band="default") is None  # depth-bound shed
        lv = (("priority_band", "default"), ("reason", "depth-bound"),
              ("shard", "97"))
        assert PODS_SHED_TOTAL.collect().get(lv) == 1.0
        assert b.shed_total() == 1
        assert b.shed[("depth-bound", "default")] == 1

    def test_depth_gauge_emits_per_shard_series(self):
        b = Batcher(idle_seconds=0.05, max_seconds=0.5, max_depth=10)
        b.shard = "98"
        b.add("x")
        b.add("y")
        assert INTAKE_QUEUE_DEPTH.collect().get((("shard", "98"),)) == 2.0

    def test_unsharded_batcher_emits_legacy_unlabeled_shed(self):
        before = PODS_SHED_TOTAL.collect().get(
            (("priority_band", "default"), ("reason", "depth-bound")), 0.0)
        b = Batcher(idle_seconds=0.05, max_seconds=0.5, max_depth=1)
        b.add("first")
        b.add("second")
        after = PODS_SHED_TOTAL.collect().get(
            (("priority_band", "default"), ("reason", "depth-bound")), 0.0)
        assert after == before + 1.0


class TestRequeueJitter:
    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_bounds_determinism_and_spread(self, seed):
        rng = random.Random(seed)
        keys = [("ns-%d" % rng.randrange(10),
                 "pod-" + "".join(rng.choices(string.hexdigits, k=8)))
                for _ in range(200)]
        lo, hi = 1.0 - JITTER_SPREAD / 2, 1.0 + JITTER_SPREAD / 2
        values = [requeue_jitter(k) for k in keys]
        assert all(lo <= v < hi for v in values), \
            f"seed={seed}: jitter escaped [{lo}, {hi})"
        assert values == [requeue_jitter(k) for k in keys], \
            "jitter is not deterministic in the key"
        # the point of the jitter is de-synchronization: a mass-shed cohort
        # must NOT collapse onto a handful of retry instants
        assert max(values) - min(values) > JITTER_SPREAD / 2, \
            f"seed={seed}: cohort spread too narrow ({min(values)}..{max(values)})"
        assert len(set(values)) > 150, "jitter collides too often"

    def test_none_key_is_identity(self):
        assert requeue_jitter(None) == 1.0

    def test_requeue_seconds_applies_jitter(self):
        kube = KubeCore()
        provider = FakeCloudProvider(catalog=instance_types(2))
        provisioning = ProvisioningController(kube, provider, shards=2)
        selection = SelectionController(kube, provisioning)
        try:
            key = ("default", "some-pod")
            base = selection._requeue_seconds(None)
            assert base == selection.REQUEUE_SECONDS  # L0, no jitter for None
            jittered = selection._requeue_seconds(key)
            assert jittered == pytest.approx(base * requeue_jitter(key))
            assert jittered != base  # this key's hash is not exactly 1.0
        finally:
            for w in provisioning.workers.values():
                w.stop()
