"""Per-pod SLO engine (karpenter_tpu/obs/slo.py, ISSUE 14).

Covers: digest accuracy under fuzz (seeds 1/7/42), merge associativity
and the shard/dispatch-fetch merge law, the collapse bound (fixed memory,
tail fidelity preserved), cross-thread engine recording against a serial
oracle, the burn sentinel (trip on sustained burn, zero trips on a clean
run, trip rate-limit, sheds-as-breaches, the multi-window rule), the
flight recorder's ``slo-burn`` trigger on its independent rate-limit
clock (exactly one dump), readyz degradation while burning, window-marks
context carry, and the stamping overhead bound.
"""

from __future__ import annotations

import http.client
import json
import random
import threading

import pytest

from karpenter_tpu.obs import flight, slo
from karpenter_tpu.obs.slo import BurnSentinel, Digest, Objective, SloEngine
from tools.slo_verdict import verdict as slo_verdict


@pytest.fixture(autouse=True)
def _clean_slo():
    slo.reset()
    slo.configure(enabled=True, objectives=slo.default_objectives(),
                  fast_window_s=60.0, slow_window_s=1800.0,
                  fast_burn=6.0, slow_burn=1.0, trip_interval_s=30.0)
    flight.reset()
    yield
    slo.reset()
    slo.configure(enabled=True, objectives=slo.default_objectives(),
                  fast_window_s=60.0, slow_window_s=1800.0,
                  fast_burn=6.0, slow_burn=1.0, trip_interval_s=30.0)
    flight.reset()
    flight.configure(dir="", min_interval_s=5.0)


def _exact_quantile(vs, q):
    """The replay report's rank convention — the digest promises to land
    within alpha relative error of THIS number."""
    vs = sorted(vs)
    return vs[min(len(vs) - 1, int(len(vs) * q))]


class TestDigest:
    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_fuzz_quantiles_within_1pct(self, seed):
        rng = random.Random(seed)
        d = Digest()
        vs = [rng.lognormvariate(0.0, 2.0) for _ in range(20_000)]
        for v in vs:
            d.record(v)
        for q in (0.5, 0.9, 0.99, 0.999):
            exact = _exact_quantile(vs, q)
            est = d.quantile(q)
            assert abs(est - exact) / exact <= 0.01, \
                f"seed {seed} q{q}: {est} vs exact {exact}"
        assert d.n == len(vs)
        top = max(vs)
        assert abs(d.quantile(1.0) - top) / top <= 0.01

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_merge_associative_and_exact(self, seed):
        rng = random.Random(seed)
        parts = [[rng.expovariate(1.0) for _ in range(777)] for _ in range(3)]
        digs = []
        for part in parts:
            d = Digest()
            for v in part:
                d.record(v)
            digs.append(d)
        left = digs[0].copy().merge(digs[1]).merge(digs[2])
        right = digs[0].copy().merge(digs[1].copy().merge(digs[2]))
        # bucket counts are integers: merge must be EXACTLY associative
        # (total is a float sum — order-sensitive — so not compared)
        assert left.counts == right.counts
        assert (left.n, left.zero) == (right.n, right.zero)
        assert (left.vmin, left.vmax) == (right.vmin, right.vmax)
        # and the merge must equal recording everything into one digest
        one = Digest()
        for part in parts:
            for v in part:
                one.record(v)
        assert left.counts == one.counts and left.n == one.n

    def test_record_n_equals_repeated_record(self):
        a, b = Digest(), Digest()
        a.record_n(0.125, 5)
        a.record_n(3.5, 2)
        for v in (0.125,) * 5 + (3.5,) * 2:
            b.record(v)
        assert a.counts == b.counts
        assert (a.n, a.zero, a.vmin, a.vmax) == (b.n, b.zero, b.vmin, b.vmax)

    def test_collapse_bounds_memory_keeps_tail(self):
        rng = random.Random(7)
        d = Digest(max_bins=512)
        vs = [rng.lognormvariate(0.0, 3.0) for _ in range(50_000)]
        for v in vs:
            d.record(v)
        # ~1500 natural buckets for this spread: the collapse must have
        # actually fired and held the budget
        assert d.bins() <= 512, "collapse must hold the bin budget"
        # low buckets fold upward, so quantiles above the collapsed
        # region — the tail the SLO reads — keep the accuracy promise
        for q in (0.99, 0.999):
            exact = _exact_quantile(vs, q)
            assert abs(d.quantile(q) - exact) / exact <= 0.01, q
        # below the fold the estimate may only err HIGH (mass moved up),
        # never low — a breach can't be hidden by the collapse
        assert d.quantile(0.05) >= _exact_quantile(vs, 0.05) * 0.99

    def test_zero_bucket_and_empty(self):
        d = Digest()
        assert d.report() == {"p50": 0.0, "p99": 0.0, "max": 0.0, "n": 0}
        for _ in range(10):
            d.record(0.0)
        d.record(5.0)
        assert d.zero == 10
        assert d.quantile(0.5) == 0.0
        assert abs(d.quantile(1.0) - 5.0) / 5.0 <= 0.01
        assert d.report()["max"] == 5.0

    def test_roundtrip_and_alpha_mismatch(self):
        d = Digest()
        for v in (0.1, 1.0, 10.0):
            d.record(v)
        back = Digest.from_dict(json.loads(json.dumps(d.to_dict())))
        assert back.counts == d.counts and back.n == d.n
        with pytest.raises(ValueError):
            d.merge(Digest(alpha=0.02))


class TestEngine:
    def test_cross_thread_matches_serial_oracle(self):
        """Four threads hammer the striped engine; the result must be
        bucket-identical to one thread recording the same samples."""
        eng = SloEngine()
        per_thread = 2_000

        def work(tseed):
            rng = random.Random(tseed)
            for _ in range(per_thread):
                band = rng.choice(("default", "high"))
                eng.record(band, "e2e", rng.expovariate(2.0))

        threads = [threading.Thread(target=work, args=(s,))
                   for s in (1, 7, 42, 99)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        oracle = {}
        for s in (1, 7, 42, 99):
            rng = random.Random(s)
            for _ in range(per_thread):
                band = rng.choice(("default", "high"))
                v = rng.expovariate(2.0)
                oracle.setdefault(band, Digest()).record(v)
        for band, d in oracle.items():
            got = eng.digest(band, "e2e")
            assert got is not None
            assert got.counts == d.counts and got.n == d.n, \
                f"striped recording lost samples for {band}"

    def test_merge_from_is_shard_aggregation(self):
        a, b = SloEngine(), SloEngine()
        a.record("default", "e2e", 0.5, count=10)
        b.record("default", "e2e", 2.0, count=10)
        b.record("high", "bind", 0.1)
        a.merge_from(b)
        assert a.digest("default", "e2e").n == 20
        assert a.digest("high", "bind").n == 1
        assert b.digest("high", "bind").n == 1, "source must be untouched"

    def test_growth_invariant(self):
        eng = SloEngine()
        rng = random.Random(42)
        bands = ("system-critical", "high", "default", "low", "besteffort")
        for _ in range(5_000):
            eng.record(rng.choice(bands), rng.choice(slo.STAGES),
                       rng.lognormvariate(0.0, 2.0))
        assert eng.cell_count() <= len(bands) * len(slo.STAGES)
        assert eng.total_bins() <= eng.cell_count() * eng.max_bins
        snap = eng.snapshot()
        assert snap["records"] == 5_000
        assert set(snap["stages"]) <= set(slo.STAGES)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestBurnSentinel:
    def test_trips_on_sustained_burn_with_tags(self):
        clk = _Clock()
        s = BurnSentinel({"default": Objective(1.0, target=0.99)},
                         trip_interval_s=30.0, timefunc=clk)
        for _ in range(50):
            s.observe("default", 5.0)
        burn = s.evaluate()
        assert burn["default"]["burning"]
        assert burn["default"]["fast_burn"] >= 6.0
        assert s.burning() == ["default"]
        assert s.trips_total() == 1
        tags = s.last_trip_tags()
        assert tags["band"] == "default" and tags["stage"] == "e2e"
        assert tags["objective_s"] == 1.0

    def test_clean_run_never_trips(self):
        clk = _Clock()
        s = BurnSentinel({"default": Objective(1.0)}, timefunc=clk)
        for _ in range(500):
            s.observe("default", 0.01)
        assert not s.evaluate()["default"]["burning"]
        assert s.trips_total() == 0 and s.burning() == []

    def test_trip_rate_limit_and_rearm(self):
        clk = _Clock()
        s = BurnSentinel({"default": Objective(1.0)},
                         trip_interval_s=30.0, timefunc=clk)
        for _ in range(50):
            s.observe("default", 5.0)
        s.evaluate()
        clk.t += 5.0
        s.observe("default", 5.0)
        s.evaluate()
        assert s.trips_total() == 1, "re-trip inside the interval"
        clk.t += 31.0
        s.observe("default", 5.0)
        s.evaluate()
        assert s.trips_total() == 2, "interval elapsed: sentinel re-arms"

    def test_shed_counts_as_breach(self):
        clk = _Clock()
        s = BurnSentinel({"default": Objective(60.0)}, timefunc=clk)
        for _ in range(20):
            s.observe("default", shed=True)
        assert s.breaches_total() == 20
        assert s.evaluate()["default"]["burning"], \
            "sheds burn budget without ever producing a latency sample"

    def test_bands_without_objective_ignored(self):
        s = BurnSentinel({"default": Objective(1.0)}, timefunc=_Clock())
        for _ in range(100):
            s.observe("besteffort", 999.0)
            s.observe("low", shed=True)
        assert s.evaluate() == {}
        assert s.breaches_total() == 0, \
            "pressure-ladder sheds of flood bands must not read as burn"

    def test_multi_window_rule_fast_spike_ages_out(self):
        """Breaches older than the fast window stop the fast burn even
        though the slow window still remembers them — no lingering alert."""
        clk = _Clock()
        s = BurnSentinel({"default": Objective(1.0)},
                         fast_window_s=60.0, slow_window_s=1800.0,
                         timefunc=clk)
        for _ in range(50):
            s.observe("default", 5.0)
        assert s.evaluate()["default"]["burning"]
        clk.t += 120.0  # spike ages past the fast window
        for _ in range(50):
            s.observe("default", 0.01)
        burn = s.evaluate()
        assert not burn["default"]["burning"]
        assert burn["default"]["slow_burn"] > burn["default"]["fast_burn"]
        assert s.burning() == []


class TestFlightIntegration:
    def test_slo_burn_trips_exactly_one_dump(self, tmp_path):
        """Regression: the slo-burn trigger rides an INDEPENDENT
        rate-limit clock — a prior watchdog dump must not swallow it,
        and rapid re-evaluation must not double-dump."""
        flight.configure(dir=str(tmp_path), min_interval_s=5.0)
        flight.trip("watchdog-trip", reason="warm-up-the-shared-clock")
        slo.configure(objectives={"default": Objective(0.001)},
                      trip_interval_s=0.0)
        for _ in range(50):
            slo.record("default", "e2e", 1.0)
        slo.evaluate()
        slo.evaluate()  # immediate re-trip: dump must be rate-limited
        dumps = [p for p in flight.recent_dumps() if "slo-burn" in p]
        assert len(dumps) == 1, f"expected exactly one slo-burn dump: {dumps}"
        payload = json.loads(open(dumps[0]).read())
        assert payload["trigger"] == "slo-burn"
        assert payload["tags"]["band"] == "default"
        assert payload["tags"]["stage"] == "e2e"
        assert payload["tags"]["burn_rate"] >= 6.0
        assert flight.state()["last_trigger"] == "slo-burn"

    def test_readyz_degrades_while_burning(self):
        """A burning band flips /readyz to 503 with the band named;
        /healthz (liveness) stays green — a restart would only hurt."""
        from http.server import HTTPServer

        from karpenter_tpu.main import _Handler

        slo.configure(objectives={"default": Objective(0.001)})
        for _ in range(50):
            slo.record("default", "e2e", 1.0)
        slo.evaluate()
        assert slo.burning() == ["default"]

        _Handler.manager = None
        srv = HTTPServer(("127.0.0.1", 0), _Handler)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()
        try:
            def get(path):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.server_address[1], timeout=5.0)
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read().decode()
                conn.close()
                return resp.status, body

            status, body = get("/readyz")
            assert status == 503
            assert "slo-burn=default" in body
            status, body = get("/healthz")
            assert status == 200 and body.startswith("ok")

            slo.reset()
            status, body = get("/readyz")
            assert status == 200, "recovered burn must restore readiness"
        finally:
            srv.shutdown()
            thread.join(timeout=5.0)

    def test_debug_vars_carries_slo_state(self):
        from karpenter_tpu.main import debug_vars

        slo.record("default", "e2e", 0.25)
        dv = debug_vars()
        assert dv["slo"]["enabled"] is True
        assert dv["slo"]["engine"]["records"] >= 1
        assert "objectives" in dv["slo"]["burn"]


class TestModuleApi:
    def test_disabled_records_nothing(self):
        slo.disable()
        before = slo.record_calls()
        slo.record("default", "e2e", 1.0)
        slo.note_shed("default")
        assert slo.record_calls() == before
        assert slo.engine().records_total() == 0
        assert slo.sentinel().breaches_total() == 0

    def test_marks_carry_across_threads(self):
        pod = object()
        marks = slo.WindowMarks(t_close=12.5, meta={id(pod): ("high", 0.3)})
        seen = {}

        def fetch_side():
            with slo.use_marks(marks):
                seen["marks"] = slo.current_marks()
            seen["after"] = slo.current_marks()

        t = threading.Thread(target=fetch_side)
        t.start()
        t.join()
        assert seen["marks"] is marks
        assert seen["marks"].meta[id(pod)] == ("high", 0.3)
        assert seen["after"] is None
        with slo.use_marks(None):  # no-op carry must not clobber
            assert slo.current_marks() is None

    def test_overhead_is_bounded(self):
        """The pipeline verdict gates measured-calls × ns/call at < 1% of
        the stamped wall; here pin the per-call costs to sane ceilings so
        a 100× stamping regression fails fast in tier-1."""
        over = slo.measure_overhead(n=5_000)
        assert over["disabled_ns_per_record"] < 5_000, over
        assert over["enabled_ns_per_record"] < 100_000, over
        # ~20 stamp calls per provisioning window (bands × stages + e2e):
        # even a 10ms window keeps the tax well under the 1% gate
        assert 20 * over["enabled_ns_per_record"] / 1e9 < 0.01 * 0.010, over


class TestSloVerdict:
    def _line(self, **kw):
        replay = {
            "pending_to_bound_s": {"default": {"p50": 0.1, "p99": 0.7,
                                               "max": 1.0, "n": 100}},
            "slo": {"records": 100, "cells": 5, "total_bins": 50,
                    "bounded": True, "burning": [], "trips": 0,
                    "burn": {"objectives": {"default": {
                        "threshold_s": 60.0, "target": 0.99,
                        "stage": "e2e"}}}},
            "slo_digest_parity": {"within_1pct": True,
                                  "default": {"p50_rel_err": 0.004,
                                              "p99_rel_err": 0.006}},
        }
        chaos = {"trips": 1, "readyz_degraded": True,
                 "last_trip": {"band": "default", "stage": "e2e"}}
        line = {"replay": replay, "slo_chaos": chaos}
        line.update(kw)
        return line

    def test_pass_shape(self):
        v = slo_verdict(self._line())
        assert "PASS" in v and "FAIL" not in v, v
        assert "parity=0.60%" in v and "chaos trips=1" in v

    def test_clean_trip_fails(self):
        line = self._line()
        line["replay"]["slo"]["trips"] = 2
        v = slo_verdict(line)
        assert "FAIL" in v and "clean leg" in v

    def test_unbounded_growth_fails(self):
        line = self._line()
        line["replay"]["slo"]["bounded"] = False
        assert "UNBOUNDED" in slo_verdict(line)

    def test_p99_over_objective_fails(self):
        line = self._line()
        line["replay"]["pending_to_bound_s"]["default"]["p99"] = 61.0
        v = slo_verdict(line)
        assert "FAIL" in v and "objective" in v

    def test_chaos_never_tripping_fails(self):
        line = self._line()
        line["slo_chaos"] = {"trips": 0, "readyz_degraded": False,
                             "last_trip": None}
        v = slo_verdict(line)
        assert "FAIL" in v and "never tripped" in v

    def test_absent_probe_and_parity_are_na(self):
        line = self._line()
        line["slo_chaos"] = None
        del line["replay"]["slo_digest_parity"]
        v = slo_verdict(line)
        assert "PASS" in v and "parity=n/a" in v and "chaos n/a" in v
