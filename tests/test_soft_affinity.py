"""Topology-keyed pair planes + preferred (soft) pod-affinity scoring.

ISSUE 20 contracts under test:

- TOPOLOGY-KEYED INJECTION FUZZ: seeded windows (1/7/42, >=540 cases
  total) of pods carrying required zone-/node-group-/hostname-keyed
  (anti-)affinity run through AffinityGroups.inject; the final
  assignment must satisfy the scalar ``LabelSelector.matches`` oracle on
  every pair — co-located sets share one interned topology value drawn
  from the provisioner vocabulary, anti pairs get distinct values,
  impossible components shed with the unsat marker. Zero divergence.
- PREFERRED-TERM SCORING FUZZ: fused windows with random zone vote maps
  scored by ops/policy.score_fused_window must equal an independent
  scalar oracle over raw offerings (exact int micro-$, same fixed
  point) on every cell, with zero soft-affinity-mismatch fallbacks on
  clean runs.
- VERDICT IS A FILTER: a sabotaged device row on a soft window is caught
  by the probe, counted as ``policy_fallback_total{reason=
  "soft-affinity-mismatch"}``, and healed to the host mirror.
- KILL SWITCH: KARPENTER_SOFT_AFFINITY=0 produces bit-for-bit the
  no-preference rows, injects no votes, steers no launches, and prices
  no consolidation loss.
- CONSOLIDATION: a drain that scatters a preferred co-located set is
  blocked exactly when its soft-affinity loss >= the price savings.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import (
    Affinity, LabelSelector, PodAffinity, PodAffinityTerm,
    WeightedPodAffinityTerm,
)
from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.metrics.policy import POLICY_FALLBACK_TOTAL
from karpenter_tpu.models.cost import CostConfig
from karpenter_tpu.ops import device_filter
from karpenter_tpu.ops import policy as ops_policy
from karpenter_tpu.scheduling.affinity import AffinityGroups, soft_enabled
from karpenter_tpu.solver import policy as policy_registry
from karpenter_tpu.solver.adapter import marshal_pods_interned
from karpenter_tpu.solver.batch_solve import Problem
from karpenter_tpu.solver.policy import PolicyContext, soft_zone_votes
from karpenter_tpu.solver.solve import (
    SolverConfig, resolved_device_max_shapes,
)
from tests.test_pack_parity import make_pod
from tests.test_policy import _catalog

SEEDS = (1, 7, 42)
ZONES = ("test-zone-1", "test-zone-2", "test-zone-3")
_LBL_KEYS = ("app", "tier", "track")
_LBL_VALS = ("web", "db", "cache", "batch", "canary")
_TOPO_KEYS = (wellknown.LABEL_TOPOLOGY_ZONE, wellknown.LABEL_HOSTNAME)


def _pod(name, labels, aff_terms=(), anti_terms=(), preferred=()):
    p = make_pod({"cpu": "100m", "memory": "64Mi"})
    p.metadata.name = name
    p.metadata.namespace = "default"
    p.metadata.labels = dict(labels)
    if aff_terms or anti_terms or preferred:
        aff = Affinity()
        if aff_terms or preferred:
            aff.pod_affinity = PodAffinity(
                required=list(aff_terms),
                preferred=[WeightedPodAffinityTerm(weight=w, term=t)
                           for w, t in preferred])
        if anti_terms:
            aff.pod_anti_affinity = PodAffinity(required=list(anti_terms))
        p.spec.affinity = aff
    return p


def _rand_term(rng):
    key = rng.choice(_TOPO_KEYS)
    sel = LabelSelector(match_labels={
        rng.choice(_LBL_KEYS): rng.choice(_LBL_VALS)})
    return PodAffinityTerm(topology_key=key, label_selector=sel)


def _rand_window(rng):
    pods = []
    for i in range(rng.randint(3, 9)):
        labels = {k: rng.choice(_LBL_VALS)
                  for k in rng.sample(_LBL_KEYS, rng.randint(1, 2))}
        aff, anti = [], []
        roll = rng.random()
        if roll < 0.45:
            aff.append(_rand_term(rng))
        elif roll < 0.75:
            anti.append(_rand_term(rng))
        if rng.random() < 0.15:
            anti.append(_rand_term(rng))
        pods.append(_pod(f"p{i}", labels, aff, anti))
    return pods


def _required_of(pod, anti):
    aff = getattr(pod.spec, "affinity", None)
    side = getattr(aff, "pod_anti_affinity" if anti else "pod_affinity",
                   None) if aff else None
    return [t for t in (getattr(side, "required", None) or [])
            if t.topology_key and t.label_selector is not None]


class TestTopologyKeyedInjectionFuzz:
    """Seeded fuzz of the full injection path: the final (value or unsat)
    assignment per pod must satisfy the scalar matches() oracle."""

    def _constraints(self):
        return universe_constraints(instance_types(5))

    @pytest.mark.parametrize("seed", SEEDS)
    def test_assignment_satisfies_scalar_oracle(self, seed):
        rng = random.Random(seed)
        cases = 180
        for _ in range(cases):
            cons = self._constraints()
            pods = _rand_window(rng)
            AffinityGroups().inject(cons, pods)
            unsat = {id(p) for p in pods
                     if p.__dict__.get("_affinity_unsat")}
            for p in pods:
                if id(p) in unsat:
                    # the unsat marker must shed at validation: hostname
                    # pinned to a value outside every vocabulary
                    assert p.spec.node_selector.get(
                        wellknown.LABEL_HOSTNAME) == ""
                    continue
                for term in _required_of(p, anti=False):
                    v = p.spec.node_selector.get(term.topology_key)
                    if v is not None and \
                            term.topology_key != wellknown.LABEL_HOSTNAME:
                        vocab = cons.requirements.requirement(
                            term.topology_key)
                        assert vocab is not None and v in vocab, \
                            f"{v!r} not in provisioner vocabulary"
                    # every matching live peer shares the domain value
                    # (singletons with only a self-anchor keep no pin)
                    for q in pods:
                        if q is p or id(q) in unsat:
                            continue
                        if q.metadata.namespace != p.metadata.namespace:
                            continue
                        if term.label_selector.matches(q.metadata.labels):
                            assert v is not None, (
                                f"{p.metadata.name} has live matching peer "
                                f"{q.metadata.name} but no "
                                f"{term.topology_key} pin")
                            assert q.spec.node_selector.get(
                                term.topology_key) == v, (
                                f"{p.metadata.name} and matching peer "
                                f"{q.metadata.name} split across "
                                f"{term.topology_key} domains")
                for term in _required_of(p, anti=True):
                    v = p.spec.node_selector.get(term.topology_key)
                    for q in pods:
                        if q is p or id(q) in unsat:
                            continue
                        if q.metadata.namespace != p.metadata.namespace:
                            continue
                        if term.label_selector.matches(q.metadata.labels):
                            qv = q.spec.node_selector.get(term.topology_key)
                            assert v and qv and v != qv, (
                                f"anti pair {p.metadata.name}/"
                                f"{q.metadata.name} shares domain {v!r}")

    def test_zone_vocabulary_never_invented(self):
        """Valued-key domains are interned values, never fresh tokens:
        every injected zone comes from the provisioner requirement."""
        rng = random.Random(42)
        for _ in range(60):
            cons = self._constraints()
            pods = _rand_window(rng)
            AffinityGroups().inject(cons, pods)
            vocab = cons.requirements.requirement(
                wellknown.LABEL_TOPOLOGY_ZONE)
            for p in pods:
                v = p.spec.node_selector.get(wellknown.LABEL_TOPOLOGY_ZONE)
                if v is not None and not p.__dict__.get("_affinity_unsat"):
                    assert v in vocab

    def test_node_group_key_uses_provisioner_vocabulary(self):
        from karpenter_tpu.api.core import NodeSelectorRequirement as Req
        cons = self._constraints()
        cons.requirements = cons.requirements.add(Req(
            key=wellknown.LABEL_NODE_GROUP, operator="In",
            values=["pool-a", "pool-b"]))
        sel = LabelSelector(match_labels={"app": "web"})
        term = PodAffinityTerm(topology_key=wellknown.LABEL_NODE_GROUP,
                               label_selector=sel)
        a = _pod("a", {"app": "web"}, aff_terms=[term])
        b = _pod("b", {"app": "web"})
        AffinityGroups().inject(cons, [a, b])
        va = a.spec.node_selector.get(wellknown.LABEL_NODE_GROUP)
        vb = b.spec.node_selector.get(wellknown.LABEL_NODE_GROUP)
        assert va == vb and va in ("pool-a", "pool-b")

    def test_no_vocabulary_sheds(self):
        # a topology key the provisioner has no requirement for cannot
        # host a domain: the component sheds instead of inventing values
        sel = LabelSelector(match_labels={"app": "web"})
        term = PodAffinityTerm(topology_key="example.com/unheard-of",
                               label_selector=sel)
        cons = self._constraints()
        a = _pod("a", {"app": "web"}, aff_terms=[term])
        b = _pod("b", {"app": "web"})
        AffinityGroups().inject(cons, [a, b])
        assert a.__dict__.get("_affinity_unsat")
        assert b.__dict__.get("_affinity_unsat")
        assert a.spec.node_selector.get(wellknown.LABEL_HOSTNAME) == ""


def _soft_oracle_row(it, reqs, votes, ctx, cost_config, use_soft):
    """Independent scalar score of one type: min over allowed offerings
    of sat(micro(price_ct) + min-over-viable-zones clamp(-w x scale)),
    floored at 0 — the device kernel's contract, from raw offerings."""
    zones = reqs.zones()
    cts = reqs.capacity_types()
    scale = int(round(ctx.soft_affinity_cost_per_weight * 1e6))
    imax = int(ops_policy._INT32_MAX)
    clamp = ops_policy._SOFT_CLAMP
    best = imax
    for ct in {o.capacity_type for o in it.offerings}:
        if cts is not None and ct not in cts:
            continue
        viable = [o.zone for o in it.offerings
                  if o.capacity_type == ct
                  and (zones is None or o.zone in zones)]
        if not viable:
            continue
        base = it.price * cost_config.spot_price_factor \
            if ct == wellknown.CAPACITY_TYPE_SPOT else it.price
        cell = int(ops_policy._encode_micro(base))
        if use_soft:
            adj = min(max(-clamp, min(-votes.get(z, 0) * scale, clamp))
                      for z in viable)
            cell = max(0, min(cell + adj, imax))
        best = min(best, cell)
    return best


def _soft_problems(catalog, seed, n=4):
    """Problems mixing pinned and open zones, each with a random (possibly
    empty) zone vote map riding Problem.soft_affinity."""
    from karpenter_tpu.api.core import NodeSelectorRequirement as Req
    rng = random.Random(seed)
    constraints = universe_constraints(catalog)
    zones = sorted({o.zone for it in catalog for o in it.offerings})
    problems = []
    for b in range(n):
        tightened = constraints.deepcopy()
        if rng.random() < 0.5:
            tightened.requirements = tightened.requirements.add(Req(
                key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
                values=[rng.choice(zones)]))
        pods = []
        for j in range(rng.randint(30, 80)):
            pods.append(make_pod({
                "cpu": f"{rng.choice([100, 250, 500])}m",
                "memory": f"{rng.choice([128, 512])}Mi"}))
            pods[-1].metadata.name = f"p{b}-{j}"
        soft = None
        if rng.random() < 0.75:
            soft = {(wellknown.LABEL_TOPOLOGY_ZONE, z):
                    rng.choice([-100, -7, 1, 42, 100])
                    for z in rng.sample(zones, rng.randint(1, len(zones)))}
        problems.append(Problem(constraints=tightened, pods=pods,
                                instance_types=catalog,
                                soft_affinity=soft))
    return problems


def _fused(problems, config):
    marshaled = [marshal_pods_interned(p.pods) for p in problems]
    return device_filter.prepare_fused(
        problems, marshaled, config, resolved_device_max_shapes(config))


class TestPreferredScoringFuzz:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_rows_match_scalar_oracle(self, seed):
        """score_fused_window with soft votes vs the independent scalar
        oracle: exact int equality on every (member, type) cell, zero
        soft-affinity-mismatch fallbacks burned."""
        catalog = _catalog(seed=seed)
        config = SolverConfig(device_min_pods=1)
        problems = _soft_problems(catalog, seed)
        fused = _fused(problems, config)
        if fused is None:
            pytest.skip("no device backend for the fused window")
        mm_key = (("reason", "soft-affinity-mismatch"),)
        before = POLICY_FALLBACK_TOTAL.collect().get(mm_key, 0.0)
        try:
            ctx = PolicyContext(soft_affinity_cost_per_weight=0.001)
            policy = policy_registry.get("cheapest")
            rows = ops_policy.score_fused_window(
                fused, policy, config.cost_config, ctx)
            assert rows is not None
            use_soft = soft_enabled() and any(
                any(soft_zone_votes(s).values())
                for s in fused.soft if s is not None)
            assert use_soft, "fuzz window carried no usable votes"
            div = 0
            for b, i in enumerate(fused.batch_idx):
                reqs = problems[i].constraints.requirements
                votes = soft_zone_votes(problems[i].soft_affinity)
                for k, p in enumerate(fused.packables):
                    want = _soft_oracle_row(
                        fused.uni_types[p.index], reqs, votes, ctx,
                        config.cost_config, use_soft)
                    div += int(int(rows[b][k]) != want)
            assert div == 0, f"{div} cells diverged from the scalar oracle"
        finally:
            fused.release()
        assert POLICY_FALLBACK_TOTAL.collect().get(mm_key, 0.0) == before

    def test_zero_weight_context_is_bit_for_bit_plain(self):
        """soft_affinity_cost_per_weight=0 disables pricing entirely: the
        rows equal the no-votes rows exactly (weight-0 fixed point)."""
        catalog = _catalog(seed=1)
        config = SolverConfig(device_min_pods=1)
        problems = _soft_problems(catalog, 1)
        plain = [Problem(constraints=p.constraints, pods=p.pods,
                         instance_types=p.instance_types)
                 for p in problems]
        fused_soft = _fused(problems, config)
        fused_plain = _fused(plain, config)
        if fused_soft is None or fused_plain is None:
            pytest.skip("no device backend for the fused window")
        try:
            policy = policy_registry.get("cheapest")
            zero = PolicyContext(soft_affinity_cost_per_weight=0.0)
            on = ops_policy.score_fused_window(
                fused_soft, policy, config.cost_config, zero)
            off = ops_policy.score_fused_window(
                fused_plain, policy, config.cost_config, zero)
            assert on is not None and off is not None
            for a, b in zip(on, off):
                assert np.array_equal(a, b)
        finally:
            fused_soft.release()
            fused_plain.release()


class TestSoftSabotageSelfHeal:
    def test_sabotaged_soft_rows_heal_to_host_mirror(self, monkeypatch):
        """A corrupted device verdict on a soft window must not survive:
        the probe condemns the member as soft-affinity-mismatch and the
        returned row is the host mirror's (which the fuzz pins to the
        scalar oracle)."""
        catalog = _catalog(seed=7)
        config = SolverConfig(device_min_pods=1)
        problems = _soft_problems(catalog, 7)
        # every member votes, so every condemned member counts as a
        # soft-affinity (not plain score) mismatch
        zones = sorted({o.zone for it in catalog for o in it.offerings})
        for p in problems:
            p.soft_affinity = {(wellknown.LABEL_TOPOLOGY_ZONE, zones[0]): 50}
        fused = _fused(problems, config)
        if fused is None:
            pytest.skip("no device backend for the fused window")

        real = ops_policy._score_jit

        def sabotaged(spot_idx, use_pen, use_soft=False):
            fn = real(spot_idx, use_pen, use_soft)

            def wrapper(*args):
                best, ncells = fn(*args)
                # off-by-one on every cell: any probed column sees it
                return np.asarray(best) + np.int32(1), ncells

            return wrapper

        monkeypatch.setattr(ops_policy, "_score_jit", sabotaged)
        mm_key = (("reason", "soft-affinity-mismatch"),)
        before = POLICY_FALLBACK_TOTAL.collect().get(mm_key, 0.0)
        try:
            ctx = PolicyContext(soft_affinity_cost_per_weight=0.001)
            policy = policy_registry.get("cheapest")
            rows = ops_policy.score_fused_window(
                fused, policy, config.cost_config, ctx)
            assert rows is not None
            after = POLICY_FALLBACK_TOTAL.collect().get(mm_key, 0.0)
            assert after == before + len(fused.batch_idx), \
                "sabotage not condemned on every member"
            # healed rows equal the scalar oracle
            for b, i in enumerate(fused.batch_idx):
                reqs = problems[i].constraints.requirements
                votes = soft_zone_votes(problems[i].soft_affinity)
                for k, p in enumerate(fused.packables):
                    want = _soft_oracle_row(
                        fused.uni_types[p.index], reqs, votes, ctx,
                        config.cost_config, True)
                    assert int(rows[b][k]) == want
        finally:
            fused.release()


class TestSoftKillSwitch:
    def test_kill_switch_rows_bit_for_bit_plain(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOFT_AFFINITY", "0")
        assert not soft_enabled()
        catalog = _catalog(seed=42)
        config = SolverConfig(device_min_pods=1)
        problems = _soft_problems(catalog, 42)
        plain = [Problem(constraints=p.constraints, pods=p.pods,
                         instance_types=p.instance_types)
                 for p in problems]
        fused_soft = _fused(problems, config)
        fused_plain = _fused(plain, config)
        if fused_soft is None or fused_plain is None:
            pytest.skip("no device backend for the fused window")
        try:
            ctx = PolicyContext(soft_affinity_cost_per_weight=0.001)
            policy = policy_registry.get("cheapest")
            on = ops_policy.score_fused_window(
                fused_soft, policy, config.cost_config, ctx)
            off = ops_policy.score_fused_window(
                fused_plain, policy, config.cost_config, ctx)
            assert on is not None and off is not None
            for a, b in zip(on, off):
                assert np.array_equal(a, b)
        finally:
            fused_soft.release()
            fused_plain.release()

    def test_kill_switch_injects_no_votes(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOFT_AFFINITY", "0")
        sel = LabelSelector(match_labels={"app": "db"})
        term = PodAffinityTerm(
            topology_key=wellknown.LABEL_TOPOLOGY_ZONE, label_selector=sel)
        a = _pod("a", {"app": "web"}, preferred=[(50, term)])
        b = _pod("b", {"app": "db"})
        b.spec.node_selector = {wellknown.LABEL_TOPOLOGY_ZONE: ZONES[0]}
        cons = universe_constraints(instance_types(5))
        AffinityGroups().inject(cons, [a, b])
        assert a.__dict__.get("_soft_affinity") is None

    def test_kill_switch_steers_nothing(self, monkeypatch):
        monkeypatch.setenv("KARPENTER_SOFT_AFFINITY", "0")
        catalog = _catalog(seed=1)
        cons = universe_constraints(catalog)
        soft = {(wellknown.LABEL_TOPOLOGY_ZONE, "zone-1"): 100}
        assert ops_policy.steer_zone(
            catalog, cons.requirements, CostConfig(),
            PolicyContext(), soft) is None

    def test_kill_switch_prices_no_loss(self, monkeypatch):
        from karpenter_tpu.ops.whatif import soft_affinity_loss
        monkeypatch.setenv("KARPENTER_SOFT_AFFINITY", "0")
        sel = LabelSelector(match_labels={"app": "db"})
        term = PodAffinityTerm(topology_key=wellknown.LABEL_HOSTNAME,
                               label_selector=sel)
        a = _pod("a", {"app": "web"}, preferred=[(100, term)])
        b = _pod("b", {"app": "db"})
        from tests.test_consolidation import priced_catalog, running_node
        node = running_node("n1", priced_catalog()[0])
        assert soft_affinity_loss(node, [a], [node],
                                  {"n1": [a, b]}, 0.01) == 0.0


class TestSteerZone:
    def test_positive_vote_steers_to_voted_zone(self):
        catalog = _catalog(seed=1)
        cons = universe_constraints(catalog)
        soft = {(wellknown.LABEL_TOPOLOGY_ZONE, "zone-2"): 100}
        z = ops_policy.steer_zone(catalog, cons.requirements, CostConfig(),
                                  PolicyContext(), soft)
        assert z == "zone-2"

    def test_pinned_zone_is_never_steered(self):
        from karpenter_tpu.api.core import NodeSelectorRequirement as Req
        catalog = _catalog(seed=1)
        cons = universe_constraints(catalog)
        reqs = cons.requirements.add(Req(
            key=wellknown.LABEL_TOPOLOGY_ZONE, operator="In",
            values=["zone-1"]))
        soft = {(wellknown.LABEL_TOPOLOGY_ZONE, "zone-2"): 100}
        assert ops_policy.steer_zone(catalog, reqs, CostConfig(),
                                     PolicyContext(), soft) is None

    def test_saturated_tie_resolves_to_voted_zone(self):
        # price-0 catalog: every offering encodes to micro-$ 0 and the
        # saturation floor erases the vote discount — all zones tie at
        # total 0. The tie must land on the voted zone, not the
        # alphabetically-first one (the e2e regression: web followers
        # steered to test-zone-1 while their anchors sat in test-zone-2).
        catalog = instance_types(5)
        assert all(it.price == 0.0 for it in catalog)
        cons = universe_constraints(catalog)
        soft = {(wellknown.LABEL_TOPOLOGY_ZONE, "test-zone-2"): 80}
        z = ops_policy.steer_zone(catalog, cons.requirements, CostConfig(),
                                  PolicyContext(), soft)
        assert z == "test-zone-2"

    def test_irrelevant_votes_do_not_narrow(self):
        catalog = _catalog(seed=1)
        cons = universe_constraints(catalog)
        soft = {(wellknown.LABEL_TOPOLOGY_ZONE, "nowhere-zone"): 100}
        assert ops_policy.steer_zone(catalog, cons.requirements,
                                     CostConfig(), PolicyContext(),
                                     soft) is None


class TestConsolidationSoftBlock:
    """A drain that scatters a preferred co-located set pays its
    soft-affinity loss out of the savings — and is blocked entirely when
    the loss meets or beats them."""

    def _env(self, cost_per_weight):
        from karpenter_tpu.cloudprovider.fake.provider import (
            FakeCloudProvider,
        )
        from karpenter_tpu.controllers.consolidation import (
            ConsolidationController,
        )
        from karpenter_tpu.runtime.kubecore import KubeCore
        from tests.expectations import make_provisioner
        from tests.test_consolidation import (
            priced_catalog, running_node, running_pod,
        )
        kube = KubeCore()
        catalog = priced_catalog()
        provider = FakeCloudProvider(catalog=catalog)
        provisioner = make_provisioner(
            constraints=universe_constraints(catalog),
            consolidation_enabled=True)
        kube.create(provisioner)
        # node-0 is the priciest node in the fleet so every greedy leg
        # ranks it first — unless the soft-affinity loss filters it out
        for i, it in enumerate((catalog[2], catalog[1], catalog[1])):
            node = running_node(f"node-{i}", it)
            node.metadata.finalizers.append(wellknown.TERMINATION_FINALIZER)
            kube.create(node)
        # node-0: the preferred co-located pair (app=web wants app=db on
        # the same host, weight 100); survivors carry filler load
        sel = LabelSelector(match_labels={"app": "db"})
        term = PodAffinityTerm(topology_key=wellknown.LABEL_HOSTNAME,
                               label_selector=sel)
        web = running_pod("web-0", cpu="500m")
        web.metadata.labels = {"app": "web"}
        web.spec.affinity = Affinity(pod_affinity=PodAffinity(
            preferred=[WeightedPodAffinityTerm(weight=100, term=term)]))
        db = running_pod("db-0", cpu="500m")
        db.metadata.labels = {"app": "db"}
        for pod in (web, db):
            kube.create(pod)
            kube.bind_pod(pod, "node-0")
        for i in (1, 2):
            for j in range(3):
                pod = running_pod(f"pod-{i}-{j}", cpu="500m")
                kube.create(pod)
                kube.bind_pod(pod, f"node-{i}")
        controller = ConsolidationController(
            kube, provider=provider,
            soft_affinity_cost_per_weight=cost_per_weight)
        return kube, controller

    def test_loss_above_savings_blocks_drain(self):
        from karpenter_tpu.metrics.policy import (
            SOFT_AFFINITY_BLOCKED_DRAINS_TOTAL,
        )
        # loss = 100 x 0.01 = $1.00/h >= large's $0.40/h: blocked
        kube, controller = self._env(cost_per_weight=0.01)
        before = sum(SOFT_AFFINITY_BLOCKED_DRAINS_TOTAL.collect().values())
        controller.reconcile("default")
        assert kube.get("Node", "node-0", "").metadata.deletion_timestamp \
            is None, "drain scattered a co-located set it couldn't pay for"
        after = sum(SOFT_AFFINITY_BLOCKED_DRAINS_TOTAL.collect().values())
        assert after == before + 1

    def test_loss_below_savings_drains_with_netted_savings(self):
        # loss = 100 x 0.0001 = $0.01/h < $0.40/h: the drain proceeds
        kube, controller = self._env(cost_per_weight=0.0001)
        controller.reconcile("default")
        assert kube.get("Node", "node-0", "").metadata.deletion_timestamp \
            is not None

    def test_zone_scattering_also_priced(self):
        """A zone-keyed preferred term is satisfied by a peer on ANY node
        in the zone — draining the pod's node still forfeits nothing only
        if the pod can re-land in-zone; the loss oracle counts it."""
        from karpenter_tpu.ops.whatif import soft_affinity_loss
        from tests.test_consolidation import (
            priced_catalog, running_node, running_pod,
        )
        catalog = priced_catalog()
        n0 = running_node("n0", catalog[0])
        n1 = running_node("n1", catalog[0])  # same test-zone-1
        sel = LabelSelector(match_labels={"app": "db"})
        term = PodAffinityTerm(
            topology_key=wellknown.LABEL_TOPOLOGY_ZONE, label_selector=sel)
        web = running_pod("web", cpu="250m")
        web.metadata.labels = {"app": "web"}
        web.spec.affinity = Affinity(pod_affinity=PodAffinity(
            preferred=[WeightedPodAffinityTerm(weight=40, term=term)]))
        db = running_pod("db", cpu="250m")
        db.metadata.labels = {"app": "db"}
        loss = soft_affinity_loss(
            n0, [web], [n0, n1], {"n0": [web], "n1": [db]}, 0.001)
        assert loss == pytest.approx(40 * 0.001)
        # no matching peer in the domain -> nothing to forfeit
        db.metadata.labels = {"app": "cache"}
        assert soft_affinity_loss(
            n0, [web], [n0, n1], {"n0": [web], "n1": [db]}, 0.001) == 0.0
