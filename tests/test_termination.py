"""Termination: cordon → drain (eviction queue) → provider delete → finalizer.

Mirrors pkg/controllers/termination/suite_test.go.
"""

import time

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import (
    Node, NodeSpec, NodeStatus, ObjectMeta, OwnerReference, Pod, PodSpec,
    Toleration,
)
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from tests.expectations import eventually


@pytest.fixture()
def env():
    kube = KubeCore()
    provider = FakeCloudProvider()
    controller = TerminationController(kube, provider)
    yield kube, provider, controller
    controller.stop_all()


def terminating_node(kube, name="node-1"):
    node = Node(metadata=ObjectMeta(
        name=name, namespace="",
        labels={wellknown.PROVISIONER_NAME_LABEL: "default"},
        finalizers=[wellknown.TERMINATION_FINALIZER]))
    kube.create(node)
    kube.delete("Node", name, "")  # finalizer blocks: stamps deletionTimestamp
    return kube.get("Node", name, "")


def pod_on(kube, node_name, name="p1", annotations=None, priority="",
           tolerations=None, static=False, labels=None):
    pod = Pod(
        metadata=ObjectMeta(name=name, annotations=annotations or {},
                            labels=labels or {}),
        spec=PodSpec(node_name=node_name, tolerations=tolerations or [],
                     priority_class_name=priority))
    if static:
        pod.metadata.owner_references.append(OwnerReference(kind="Node", name=node_name))
    kube.create(pod)
    return pod


class TestTermination:
    def test_terminates_empty_deleted_node(self, env):
        kube, provider, controller = env
        terminating_node(kube)
        controller.reconcile("node-1")
        with pytest.raises(NotFound):
            kube.get("Node", "node-1", "")
        assert provider.deleted == ["node-1"]

    def test_ignores_node_without_deletion(self, env):
        kube, provider, controller = env
        node = Node(metadata=ObjectMeta(
            name="live", namespace="", finalizers=[wellknown.TERMINATION_FINALIZER]))
        kube.create(node)
        controller.reconcile("live")
        assert kube.get("Node", "live", "") is not None
        assert provider.deleted == []

    def test_cordons_and_drains_then_terminates(self, env):
        kube, provider, controller = env
        terminating_node(kube)
        pod_on(kube, "node-1", "workload")
        requeue = controller.reconcile("node-1")
        assert requeue is not None  # still draining
        assert kube.get("Node", "node-1", "").spec.unschedulable
        # eviction queue deletes the pod asynchronously
        eventually(lambda: _expect_gone(kube, "Pod", "workload", "default"))
        controller.reconcile("node-1")
        with pytest.raises(NotFound):
            kube.get("Node", "node-1", "")
        assert provider.deleted == ["node-1"]

    def test_do_not_evict_blocks_drain(self, env):
        kube, provider, controller = env
        terminating_node(kube)
        pod_on(kube, "node-1", "protected",
               annotations={wellknown.DO_NOT_EVICT_ANNOTATION: "true"})
        requeue = controller.reconcile("node-1")
        assert requeue is not None
        assert kube.get("Pod", "protected") is not None
        assert provider.deleted == []

    def test_static_pods_do_not_block(self, env):
        kube, provider, controller = env
        terminating_node(kube)
        pod_on(kube, "node-1", "mirror", static=True)
        controller.reconcile("node-1")
        with pytest.raises(NotFound):
            kube.get("Node", "node-1", "")

    def test_unschedulable_tolerating_pods_do_not_block(self, env):
        kube, provider, controller = env
        terminating_node(kube)
        pod_on(kube, "node-1", "tolerant", tolerations=[
            Toleration(key="node.kubernetes.io/unschedulable",
                       operator="Exists", effect="NoSchedule")])
        controller.reconcile("node-1")
        with pytest.raises(NotFound):
            kube.get("Node", "node-1", "")

    def test_critical_pods_evicted_last(self, env):
        kube, provider, controller = env
        terminating_node(kube)
        pod_on(kube, "node-1", "normal")
        pod_on(kube, "node-1", "critical", priority="system-node-critical")
        controller.reconcile("node-1")
        # normal goes first
        eventually(lambda: _expect_gone(kube, "Pod", "normal", "default"))
        assert kube.get("Pod", "critical") is not None
        controller.reconcile("node-1")
        eventually(lambda: _expect_gone(kube, "Pod", "critical", "default"))
        controller.reconcile("node-1")
        with pytest.raises(NotFound):
            kube.get("Node", "node-1", "")


def _expect_gone(kube, kind, name, namespace):
    try:
        kube.get(kind, name, namespace)
    except NotFound:
        return True
    raise AssertionError(f"{kind} {name} still present")


class PDBKube(KubeCore):
    """Rejects the first N evictions per pod with Conflict — the 429 PDB
    behavior the reference exercises via fake PDB misconfig
    (suite_test.go:163-199)."""

    def __init__(self, rejections=3):
        super().__init__()
        self.rejections = rejections
        self.attempts = {}

    def evict_pod(self, name, namespace="default"):
        from karpenter_tpu.runtime.kubecore import Conflict

        n = self.attempts.get((namespace, name), 0)
        self.attempts[(namespace, name)] = n + 1
        if n < self.rejections:
            raise Conflict("Cannot evict pod as it would violate the pod's "
                           "disruption budget.")
        super().evict_pod(name, namespace)


class TestEvictionBackoff:
    def test_pdb_rejections_retry_with_backoff_until_evicted(self):
        kube = PDBKube(rejections=3)
        provider = FakeCloudProvider()
        controller = TerminationController(kube, provider)
        try:
            node = terminating_node(kube)
            pod_on(kube, node.metadata.name, name="guarded")
            controller.reconcile(node.metadata.name)

            def evicted():
                names = [p.metadata.name for p in kube.list("Pod")]
                assert "guarded" not in names, f"still present: {names}"
            eventually(evicted, timeout=15.0)
            assert kube.attempts[("default", "guarded")] == 4  # 3 rejections + 1
            # drained now: next reconcile terminates the instance
            controller.reconcile(node.metadata.name)
            with pytest.raises(NotFound):
                kube.get("Node", node.metadata.name, "")
        finally:
            controller.stop_all()

    def test_real_pdb_objects_hold_then_release_drain(self):
        """PDB semantics via REAL PodDisruptionBudget objects (kubecore's
        eviction handler, r5 contract tier): a drain blocked by
        minAvailable retries with backoff (429 TooManyRequests,
        eviction.go:98-101) and completes once the budget is deleted."""
        from karpenter_tpu.api.core import LabelSelector, PodDisruptionBudget

        kube = KubeCore()
        provider = FakeCloudProvider()
        controller = TerminationController(kube, provider)
        try:
            node = terminating_node(kube)
            pod_on(kube, node.metadata.name, name="guarded",
                   labels={"app": "db"})
            kube.create(PodDisruptionBudget(
                metadata=ObjectMeta(name="db-pdb"),
                selector=LabelSelector(match_labels={"app": "db"}),
                min_available=1))
            controller.reconcile(node.metadata.name)
            time.sleep(0.5)  # several backoff rounds
            assert any(p.metadata.name == "guarded"
                       for p in kube.list("Pod")), "PDB did not hold"
            kube.delete("PodDisruptionBudget", "db-pdb", "default")

            def evicted():
                names = [p.metadata.name for p in kube.list("Pod")]
                assert "guarded" not in names, f"still present: {names}"
            eventually(evicted, timeout=15.0)
        finally:
            controller.stop_all()

    def test_pdb_misconfiguration_is_distinct_and_retries(self, caplog):
        """Two budgets selecting one pod → 500 InternalError with the
        distinct misconfiguration message (eviction.go:94-97), retried —
        not swallowed by the generic handler."""
        import logging

        from karpenter_tpu.api.core import LabelSelector, PodDisruptionBudget

        kube = KubeCore()
        provider = FakeCloudProvider()
        controller = TerminationController(kube, provider)
        try:
            node = terminating_node(kube)
            pod_on(kube, node.metadata.name, name="doubly",
                   labels={"app": "web"})
            for i in range(2):
                kube.create(PodDisruptionBudget(
                    metadata=ObjectMeta(name=f"pdb-{i}"),
                    selector=LabelSelector(match_labels={"app": "web"}),
                    min_available=0))
            with caplog.at_level(logging.DEBUG,
                                 logger="karpenter.termination"):
                controller.reconcile(node.metadata.name)
                time.sleep(0.4)
            assert any("misconfiguration" in r.message
                       for r in caplog.records), (
                "500-vs-429 distinction lost: no misconfiguration log")
            # fixing the config (one budget left) releases the drain
            kube.delete("PodDisruptionBudget", "pdb-1", "default")

            def evicted():
                names = [p.metadata.name for p in kube.list("Pod")]
                assert "doubly" not in names, f"still present: {names}"
            eventually(evicted, timeout=15.0)
        finally:
            controller.stop_all()

    def test_waits_for_terminating_pods_before_delete(self, env):
        """suite_test.go:244-303: a pod already terminating (deletion
        timestamp set, grace not expired) blocks node deletion until it is
        actually gone — without re-evicting it."""
        kube, provider, controller = env
        node = terminating_node(kube)
        pod = pod_on(kube, node.metadata.name, name="slow")
        # mark terminating: finalizer-style in-flight deletion
        stored = kube.get("Pod", "slow")
        stored.metadata.finalizers.append("example.com/block")
        kube.update(stored)
        kube.delete("Pod", "slow")

        assert controller.reconcile(node.metadata.name) == 1.0  # still draining
        assert kube.get("Node", node.metadata.name, "") is not None

        def release(p):
            p.metadata.finalizers = []
        kube.patch("Pod", "slow", "default", release)

        def gone():
            controller.reconcile(node.metadata.name)
            with pytest.raises(NotFound):
                kube.get("Node", node.metadata.name, "")
        eventually(gone, timeout=10.0)


class TestPdbIntOrString:
    """minAvailable/maxUnavailable as IntOrString (kubecore.evict_pod):
    percentages resolve against expectedPods with the apiserver's round-up;
    maxUnavailable translates to desiredHealthy = expected − resolved.

    Pods carry a finalizer so an eviction leaves them terminating instead
    of gone: expectedPods stays constant across sequential evictions (the
    real disruption controller counts terminating pods in expected but not
    in healthy), which is what makes the budgets below exact."""

    def _guarded_pods(self, kube, n):
        from karpenter_tpu.api.core import LabelSelector, PodDisruptionBudget  # noqa: F401

        for i in range(n):
            kube.create(Pod(
                metadata=ObjectMeta(name=f"guarded-{i}",
                                    labels={"app": "quorum"},
                                    finalizers=["test/block-deletion"]),
                spec=PodSpec(node_name="node-1")))

    def _pdb(self, kube, **kwargs):
        from karpenter_tpu.api.core import LabelSelector, PodDisruptionBudget

        kube.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="budget"),
            selector=LabelSelector(match_labels={"app": "quorum"}),
            **kwargs))

    def test_min_available_percentage_rounds_up(self):
        """75% of 4 rounds to desiredHealthy=3 (not floor's 2): the first
        eviction passes (4→3 healthy), the second would leave 2 < 3."""
        from karpenter_tpu.runtime.kubecore import TooManyRequests

        kube = KubeCore()
        self._guarded_pods(kube, 4)
        self._pdb(kube, min_available="75%")
        kube.evict_pod("guarded-0")
        with pytest.raises(TooManyRequests, match="3 required"):
            kube.evict_pod("guarded-1")

    def test_min_available_half_allows_down_to_the_budget(self):
        """50% of 4 → desiredHealthy=2: exactly two evictions pass."""
        from karpenter_tpu.runtime.kubecore import TooManyRequests

        kube = KubeCore()
        self._guarded_pods(kube, 4)
        self._pdb(kube, min_available="50%")
        kube.evict_pod("guarded-0")
        kube.evict_pod("guarded-1")
        with pytest.raises(TooManyRequests):
            kube.evict_pod("guarded-2")

    def test_min_available_hundred_percent_blocks_all(self):
        from karpenter_tpu.runtime.kubecore import TooManyRequests

        kube = KubeCore()
        self._guarded_pods(kube, 2)
        self._pdb(kube, min_available="100%")
        with pytest.raises(TooManyRequests):
            kube.evict_pod("guarded-0")

    def test_max_unavailable_zero_blocks_all(self):
        from karpenter_tpu.runtime.kubecore import TooManyRequests

        kube = KubeCore()
        self._guarded_pods(kube, 3)
        self._pdb(kube, max_unavailable=0)
        with pytest.raises(TooManyRequests):
            kube.evict_pod("guarded-0")

    def test_max_unavailable_int_allows_exactly_n(self):
        from karpenter_tpu.runtime.kubecore import TooManyRequests

        kube = KubeCore()
        self._guarded_pods(kube, 4)
        self._pdb(kube, max_unavailable=2)
        kube.evict_pod("guarded-0")
        kube.evict_pod("guarded-1")
        with pytest.raises(TooManyRequests):
            kube.evict_pod("guarded-2")

    def test_max_unavailable_percentage_rounds_up_the_loss_budget(self):
        """maxUnavailable=25% of 4 → resolved=1 → desiredHealthy=3: one
        eviction passes, the second is blocked."""
        from karpenter_tpu.runtime.kubecore import TooManyRequests

        kube = KubeCore()
        self._guarded_pods(kube, 4)
        self._pdb(kube, max_unavailable="25%")
        kube.evict_pod("guarded-0")
        with pytest.raises(TooManyRequests):
            kube.evict_pod("guarded-1")

    def test_setting_both_fields_is_a_500(self):
        from karpenter_tpu.runtime.kubecore import InternalError

        kube = KubeCore()
        self._guarded_pods(kube, 2)
        self._pdb(kube, min_available=1, max_unavailable=1)
        with pytest.raises(InternalError, match="both"):
            kube.evict_pod("guarded-0")

    def test_malformed_int_or_string_is_a_500(self):
        from karpenter_tpu.runtime.kubecore import InternalError

        kube = KubeCore()
        self._guarded_pods(kube, 2)
        self._pdb(kube, min_available="half")
        with pytest.raises(InternalError, match="invalid"):
            kube.evict_pod("guarded-0")

    def test_evicting_terminating_pod_never_moves_the_budget(self):
        """A pod already terminating is not healthy, so re-evicting it
        costs nothing even at the budget's edge."""
        kube = KubeCore()
        self._guarded_pods(kube, 3)
        self._pdb(kube, min_available=2)
        kube.evict_pod("guarded-0")  # 3→2 healthy: allowed, now terminating
        kube.evict_pod("guarded-0")  # loss=0: still allowed
