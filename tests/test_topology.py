"""Topology spread: zonal balancing, hostname domains, existing-pod counts.

Mirrors the topology sections of scheduling/suite_test.go.
"""

import collections

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import (
    LabelSelector, Node, NodeStatus, ObjectMeta, Pod, PodSpec,
    TopologySpreadConstraint,
)
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.scheduling.batcher import Batcher
from tests.expectations import expect_provisioned, make_provisioner, unschedulable_pod


@pytest.fixture()
def env():
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=instance_types(10))
    provisioning = ProvisioningController(
        kube, provider,
        batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
    selection = SelectionController(kube, provisioning, gate_timeout=30.0)
    provisioner = make_provisioner()
    kube.create(provisioner)
    provisioning.reconcile("default")
    yield kube, provider, provisioning, selection
    for w in provisioning.workers.values():
        w.stop()


def spread_pod(key, max_skew=1, labels=None):
    pod = unschedulable_pod(requests={"cpu": "1"})
    pod.metadata.labels = labels or {"app": "web"}
    pod.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=labels or {"app": "web"}))]
    return pod


class TestZonalTopology:
    def test_balances_across_zones(self, env):
        kube, provider, provisioning, selection = env
        pods = [spread_pod(wellknown.LABEL_TOPOLOGY_ZONE) for _ in range(9)]
        expect_provisioned(kube, selection, provisioning, pods)
        zones = collections.Counter()
        for p in pods:
            stored = kube.get("Pod", p.metadata.name)
            assert stored.spec.node_name
            node = kube.get("Node", stored.spec.node_name, "")
            zones[node.metadata.labels[wellknown.LABEL_TOPOLOGY_ZONE]] += 1
        assert len(zones) == 3  # spread over all three fake zones
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_counts_existing_pods(self, env):
        kube, provider, provisioning, selection = env
        # zone-1 already hosts two matching scheduled pods
        kube.create(Node(metadata=ObjectMeta(
            name="existing", namespace="",
            labels={wellknown.LABEL_TOPOLOGY_ZONE: "test-zone-1"})))
        for i in range(2):
            p = Pod(metadata=ObjectMeta(name=f"existing-{i}",
                                        labels={"app": "web"}),
                    spec=PodSpec(node_name="existing"))
            kube.create(p)
        pods = [spread_pod(wellknown.LABEL_TOPOLOGY_ZONE) for _ in range(4)]
        expect_provisioned(kube, selection, provisioning, pods)
        zones = collections.Counter()
        for p in pods:
            node = kube.get("Node", kube.get("Pod", p.metadata.name).spec.node_name, "")
            zones[node.metadata.labels[wellknown.LABEL_TOPOLOGY_ZONE]] += 1
        # new pods avoid the loaded zone first: zones 2/3 get 2 each
        assert zones["test-zone-1"] == 0
        assert zones["test-zone-2"] == 2 and zones["test-zone-3"] == 2


class TestHostnameTopology:
    def test_hostname_spread_forces_separate_nodes(self, env):
        kube, provider, provisioning, selection = env
        pods = [spread_pod(wellknown.LABEL_HOSTNAME) for _ in range(4)]
        expect_provisioned(kube, selection, provisioning, pods)
        nodes = {kube.get("Pod", p.metadata.name).spec.node_name for p in pods}
        assert len(nodes) == 4  # one pod per generated hostname domain

    def test_max_skew_groups_pods(self, env):
        kube, provider, provisioning, selection = env
        pods = [spread_pod(wellknown.LABEL_HOSTNAME, max_skew=2) for _ in range(4)]
        expect_provisioned(kube, selection, provisioning, pods)
        nodes = {kube.get("Pod", p.metadata.name).spec.node_name for p in pods}
        assert len(nodes) == 2  # ceil(4/2) domains
