"""Topology spread: zonal balancing, hostname domains, existing-pod counts.

Mirrors the topology sections of scheduling/suite_test.go.
"""

import collections

import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.core import (
    LabelSelector, Node, NodeStatus, ObjectMeta, Pod, PodSpec,
    TopologySpreadConstraint,
)
from karpenter_tpu.cloudprovider.fake.provider import FakeCloudProvider, instance_types
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.scheduling.batcher import Batcher
from tests.expectations import expect_provisioned, make_provisioner, unschedulable_pod


@pytest.fixture()
def env():
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=instance_types(10))
    provisioning = ProvisioningController(
        kube, provider,
        batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
    selection = SelectionController(kube, provisioning, gate_timeout=30.0)
    provisioner = make_provisioner()
    kube.create(provisioner)
    provisioning.reconcile("default")
    yield kube, provider, provisioning, selection
    for w in provisioning.workers.values():
        w.stop()


def spread_pod(key, max_skew=1, labels=None):
    pod = unschedulable_pod(requests={"cpu": "1"})
    pod.metadata.labels = labels or {"app": "web"}
    pod.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=max_skew, topology_key=key,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=labels or {"app": "web"}))]
    return pod


class TestZonalTopology:
    def test_balances_across_zones(self, env):
        kube, provider, provisioning, selection = env
        pods = [spread_pod(wellknown.LABEL_TOPOLOGY_ZONE) for _ in range(9)]
        expect_provisioned(kube, selection, provisioning, pods)
        zones = collections.Counter()
        for p in pods:
            stored = kube.get("Pod", p.metadata.name)
            assert stored.spec.node_name
            node = kube.get("Node", stored.spec.node_name, "")
            zones[node.metadata.labels[wellknown.LABEL_TOPOLOGY_ZONE]] += 1
        assert len(zones) == 3  # spread over all three fake zones
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_counts_existing_pods(self, env):
        kube, provider, provisioning, selection = env
        # zone-1 already hosts two matching scheduled pods
        kube.create(Node(metadata=ObjectMeta(
            name="existing", namespace="",
            labels={wellknown.LABEL_TOPOLOGY_ZONE: "test-zone-1"})))
        for i in range(2):
            p = Pod(metadata=ObjectMeta(name=f"existing-{i}",
                                        labels={"app": "web"}),
                    spec=PodSpec(node_name="existing"))
            kube.create(p)
        pods = [spread_pod(wellknown.LABEL_TOPOLOGY_ZONE) for _ in range(4)]
        expect_provisioned(kube, selection, provisioning, pods)
        zones = collections.Counter()
        for p in pods:
            node = kube.get("Node", kube.get("Pod", p.metadata.name).spec.node_name, "")
            zones[node.metadata.labels[wellknown.LABEL_TOPOLOGY_ZONE]] += 1
        # new pods avoid the loaded zone first: zones 2/3 get 2 each
        assert zones["test-zone-1"] == 0
        assert zones["test-zone-2"] == 2 and zones["test-zone-3"] == 2


class TestHostnameTopology:
    def test_hostname_spread_forces_separate_nodes(self, env):
        kube, provider, provisioning, selection = env
        pods = [spread_pod(wellknown.LABEL_HOSTNAME) for _ in range(4)]
        expect_provisioned(kube, selection, provisioning, pods)
        nodes = {kube.get("Pod", p.metadata.name).spec.node_name for p in pods}
        assert len(nodes) == 4  # one pod per generated hostname domain

    def test_max_skew_groups_pods(self, env):
        kube, provider, provisioning, selection = env
        pods = [spread_pod(wellknown.LABEL_HOSTNAME, max_skew=2) for _ in range(4)]
        expect_provisioned(kube, selection, provisioning, pods)
        nodes = {kube.get("Pod", p.metadata.name).spec.node_name for p in pods}
        assert len(nodes) == 2  # ceil(4/2) domains


class TestColumnarInjectParity:
    """Topology.inject's columnar path (compiled-bitset topology_allowed)
    versus the scalar leg (KARPENTER_TOPOLOGY_COLUMNAR=0): identical
    injected domains, identical unsat markers, and scalar-wins self-heal."""

    ZONE = wellknown.LABEL_TOPOLOGY_ZONE

    def _window(self):
        from karpenter_tpu.api.constraints import Constraints
        from karpenter_tpu.api.core import NodeSelectorRequirement
        from karpenter_tpu.api.requirements import Requirements

        constraints = Constraints(requirements=Requirements().add(
            NodeSelectorRequirement(
                key=self.ZONE, operator="In",
                values=["test-zone-1", "test-zone-2", "test-zone-3"])))
        pods = []
        for i in range(30):
            p = spread_pod(self.ZONE)
            p.metadata.name = f"p-{i}"
            if i % 5 == 0:
                # pinned to one viable zone: the allowed set narrows
                p.spec.node_selector[self.ZONE] = "test-zone-2"
            if i % 7 == 0:
                # outside the viable zones: no satisfiable domain
                p.spec.node_selector[self.ZONE] = "zone-nope"
            pods.append(p)
        return constraints, pods

    def test_columnar_and_scalar_legs_inject_identical_domains(self, monkeypatch):
        from karpenter_tpu.scheduling.topology import Topology

        monkeypatch.delenv("KARPENTER_TOPOLOGY_COLUMNAR", raising=False)
        c1, pods1 = self._window()
        Topology(KubeCore()).inject(c1, pods1)

        monkeypatch.setenv("KARPENTER_TOPOLOGY_COLUMNAR", "0")
        c2, pods2 = self._window()
        Topology(KubeCore()).inject(c2, pods2)

        got = [p.spec.node_selector[self.ZONE] for p in pods1]
        want = [p.spec.node_selector[self.ZONE] for p in pods2]
        assert got == want
        marks = [bool(p.__dict__.get("_topology_unsat")) for p in pods1]
        assert marks == [bool(p.__dict__.get("_topology_unsat"))
                         for p in pods2]
        # the window mixes both outcomes, so the parity is non-vacuous
        assert any(marks) and not all(marks)
        assert all(d == "" for p, d in zip(pods1, got)
                   if p.__dict__.get("_topology_unsat"))

    def test_self_heal_scalar_wins_on_columnar_divergence(self, monkeypatch):
        from karpenter_tpu.metrics.filter import FILTER_FALLBACK_TOTAL
        from karpenter_tpu.ops import feasibility
        from karpenter_tpu.scheduling import topology as topo_mod
        from karpenter_tpu.scheduling.topology import Topology

        monkeypatch.delenv("KARPENTER_TOPOLOGY_COLUMNAR", raising=False)
        # sabotage the columnar answer: claims nothing is ever allowed
        monkeypatch.setattr(topo_mod.feasibility, "topology_allowed",
                            lambda cc, sig, key: frozenset(),
                            raising=True)
        assert feasibility is topo_mod.feasibility  # same module object
        label = (("reason", "topology-mismatch"),)
        before = FILTER_FALLBACK_TOTAL.collect().get(label, 0.0)

        constraints, pods = self._window()
        satisfiable = [p for p in pods
                       if p.spec.node_selector.get(self.ZONE) != "zone-nope"]
        Topology(KubeCore()).inject(constraints, pods)

        # every satisfiable pod still landed in a real zone: scalar won
        assert all(p.spec.node_selector[self.ZONE].startswith("test-zone-")
                   for p in satisfiable)
        assert not any(p.__dict__.get("_topology_unsat") for p in satisfiable)
        assert FILTER_FALLBACK_TOTAL.collect()[label] > before

    def test_kill_switch_disables_columnar_path(self, monkeypatch):
        from karpenter_tpu.scheduling import topology as topo_mod
        from karpenter_tpu.scheduling.topology import Topology

        monkeypatch.setenv("KARPENTER_TOPOLOGY_COLUMNAR", "0")

        def boom(cc, sig, key):  # pragma: no cover - must never run
            raise AssertionError("columnar path used despite kill switch")

        monkeypatch.setattr(topo_mod.feasibility, "topology_allowed", boom,
                            raising=True)
        constraints, pods = self._window()
        Topology(KubeCore()).inject(constraints, pods)
        assert all(self.ZONE in p.spec.node_selector for p in pods)
