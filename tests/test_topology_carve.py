"""Torus-grid slice carving + priced preemption (PR 18).

Pins the carve contract end to end:

- orientation/placement-mask algebra: torus wrap, dedup, no-fit (ops/topology);
- the scalar carve oracle ``first_carve`` on fragmented/diagonal/full planes;
- seeded fuzz (seeds 1/7/42, >=510 cases, ``KARPENTER_FUZZ_CASES`` scales):
  the numpy mirror ``host_carve`` and the probe oracle ``scalar_carve_cell``
  agree with the full scalar scan on every cell — zero divergence;
- device kernel parity and the sabotage self-heal: a corrupted device
  verdict fails its probes, ``filter_fallback_total{reason="carve-mismatch"}``
  increments, and the window re-solves bit-for-bit on the scalar path;
- the PHANTOM-CAPACITY regression: pre-fix, shape-only resource math packed
  two slice gangs onto one torus whose free chips were not contiguous —
  pinned here, with the carve-aware walk rejecting the bin
  (``topology_carve_rejects_total``) and splitting the gangs;
- kill switch ``KARPENTER_TOPOLOGY_CARVE=0``: the controller encodes the
  window bit-for-bit as the annotation-free shape-only form;
- occupancy ledger commit/release/prune/snapshot isolation;
- priced preemption planning: strictly-lower-band victims only (never
  system-critical), displacement accepted exactly while its what-if price
  stays under the beneficiary's fresh-node cost, rollback on failure;
- batcher.requeue_displaced: atomic, shed-proof gang re-admission;
- e2e through the worker: carve commit -> ledger -> seed-bin reuse, and the
  full preemption lifecycle (displace, requeue, beneficiary binds, victim
  rebinds elsewhere).
"""

import os
import time

import numpy as np
import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider.fake.provider import (
    FakeCloudProvider, tpu_catalog,
)
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.metrics.filter import FILTER_FALLBACK_TOTAL
from karpenter_tpu.metrics.gang import GANGS_UNPLACEABLE_TOTAL
from karpenter_tpu.metrics.topology import (
    PREEMPTION_DECLINED_TOTAL, PREEMPTION_DISPLACED_PODS_TOTAL,
    PREEMPTIONS_TOTAL, TOPOLOGY_CARVE_REJECTS_TOTAL,
    TOPOLOGY_CARVES_COMMITTED_TOTAL,
)
from karpenter_tpu.ops import topology as topo
from karpenter_tpu.ops.gang import GangBin, encode_gang_window
from karpenter_tpu.ops.whatif import _reserve_vec
from karpenter_tpu.runtime.kubecore import KubeCore, NotFound
from karpenter_tpu.scheduling.batcher import Batcher
from karpenter_tpu.solver import gang as gang_solver
from karpenter_tpu.solver import topology as topo_solver
from karpenter_tpu.solver.gang import (
    GangConfig, PreemptCandidate, PreemptContext, plan_gang_window,
    solve_gang_window,
)
from tests.expectations import (
    eventually, expect_not_scheduled, expect_provisioned, expect_scheduled,
    make_provisioner, unschedulable_pod,
)

FUZZ_CASES = max(int(os.environ.get("KARPENTER_FUZZ_CASES", "510")) // 3, 1)


def _count(metric, **labels) -> float:
    return metric.collect().get(tuple(sorted(labels.items())), 0.0)


def _pod(name: str, cpu: str = "1", mem: str = "1Gi"):
    return unschedulable_pod(requests={"cpu": cpu, "memory": mem},
                             name=name)


def _window(gang_specs, types, seed_bins=None, grow=True):
    """Small encode helper: ``gang_specs`` = (key, n_pods, slice_dims,
    band); ``types`` = (name, price, grid). Every type's free vector is
    100x one member pod, so shape math never constrains the carve tests
    unless a case saturates it on purpose."""
    probe = _pod("probe")
    unit = [max(v, 1) for v in _reserve_vec(probe)]
    big = [v * 100 for v in unit]
    names = [t[0] for t in types]
    prices = [t[1] for t in types]
    grids = [t[2] for t in types]
    frees = [list(big) for _ in types]
    gangs, slices, bands = [], [], []
    for key, n, sdims, band in gang_specs:
        pods = [_pod(f"{key}-m{i}") for i in range(n)]
        gangs.append((key, pods, np.ones(len(types), bool), None))
        slices.append(sdims)
        bands.append(band)
    return encode_gang_window(
        gangs, frees, prices, names,
        slices=slices, bands=bands, type_grids=grids,
        seed_bins=seed_bins, grow=grow), unit, big


def _seed(name, ti, free, grid, occ):
    return GangBin(name=name, type_index=ti, free=list(free), grid=grid,
                   occ=np.asarray(occ, bool), node_name=name)


class TestPlacementMaskAlgebra:
    def test_orientations_dedup_and_unit_axes(self):
        assert topo.orientations((2, 2), 2) == ((2, 2),)
        assert set(topo.orientations((2, 4), 2)) == {(2, 4), (4, 2)}
        # unit dims pad to the host rank, so a 1x4 slice is a line either way
        assert set(topo.orientations((1, 4), 2)) == {(1, 4), (4, 1)}

    def test_masks_shapes_and_torus_wrap(self):
        assert topo.placement_masks((4, 4), (2, 2)).shape == (16, 16)
        # the full-grid slice has exactly one distinct placement
        assert topo.placement_masks((4, 4), (4, 4)).shape[0] == 1
        # a 2x4 slab wraps: 2 orientations x 16 origins dedup to 8 cell sets
        assert topo.placement_masks((4, 4), (2, 4)).shape[0] == 8
        assert topo.placement_masks((2, 2), (4, 4)) is None
        for row in topo.placement_masks((4, 4), (2, 2)):
            assert int(row.sum()) == 4

    def test_first_carve_exploits_wraparound(self):
        # occupy the grid center: only a wrapped 2x2 corner carve survives
        occ = np.zeros(16, bool)
        for r in (1, 2):
            for c in (1, 2):
                occ[r * 4 + c] = True
        cells = topo.first_carve(occ, (4, 4), (2, 2))
        assert cells is not None
        assert not occ[list(cells)].any()
        # every surviving 2x2 must wrap an axis: its row or column set is
        # non-adjacent ({0,3}), impossible without torus wraparound
        rows = {c // 4 for c in cells}
        cols = {c % 4 for c in cells}
        assert rows == {0, 3} or cols == {0, 3}

    def test_first_carve_rejects_fragmented_plane(self):
        # checkerboard: 8 free chips, no contiguous 2x2 anywhere
        occ = np.array([(r + c) % 2 == 0 for r in range(4)
                        for c in range(4)], bool)
        assert topo.first_carve(occ, (4, 4), (2, 2)) is None
        assert topo.first_carve(np.zeros(16, bool), (4, 4), (2, 2)) \
            is not None


class _FuzzGang:
    def __init__(self, index, slice_dims):
        self.index = index
        self.slice_dims = slice_dims


class _FuzzBin:
    def __init__(self, grid, occ):
        self.grid = grid
        self.occ = occ


class _FuzzEnc:
    def __init__(self, gangs, bins):
        self.gangs = gangs
        self.bins = bins
        self.g = len(gangs)
        self.b = len(bins)


GRIDS = [(2, 2), (4, 4), (2, 8), (4, 8), (2, 2, 4), (4, 4, 2), None]
SLICES = [(1, 2), (2, 2), (2, 4), (4, 4), (2, 2, 2), (8, 2), None]


class TestCarveFuzz:
    """Mirror-vs-oracle: zero divergence over random torus windows."""

    @pytest.mark.parametrize("seed", [1, 7, 42])
    def test_host_mirror_matches_scalar_oracle(self, seed):
        rng = np.random.default_rng(seed)
        for case in range(FUZZ_CASES):
            bins = []
            for _ in range(rng.integers(1, 5)):
                grid = GRIDS[rng.integers(0, len(GRIDS))]
                if grid is None:
                    bins.append(_FuzzBin(None, None))
                    continue
                c = topo.grid_cells(grid)
                occ = rng.random(c) < rng.choice([0.0, 0.3, 0.6, 0.9])
                bins.append(_FuzzBin(grid, occ))
            gangs = [
                _FuzzGang(i, SLICES[rng.integers(0, len(SLICES))])
                for i in range(rng.integers(1, 5))
            ]
            enc = _FuzzEnc(gangs, bins)
            cv = topo.encode_carve(enc)
            want = topo.scalar_carve(enc)
            if cv is None:
                assert all(g.slice_dims is None for g in gangs)
                continue
            got = topo.host_carve(cv)
            assert np.array_equal(got, want), \
                f"seed={seed} case={case}: mirror diverged from oracle"
            # the probe oracle is elementwise-consistent with the full scan
            for _ in range(4):
                gi = int(rng.integers(0, enc.g))
                bi = int(rng.integers(0, enc.b))
                assert topo.scalar_carve_cell(enc, gi, bi) == want[gi, bi]


class TestDeviceParityAndSelfHeal:
    def test_device_kernel_matches_mirror_and_oracle(self):
        enc, _, _ = _window(
            [("g0", 2, (2, 2), "default"), ("g1", 2, (4, 4), "default"),
             ("g2", 2, None, "default")],
            [("tpu-a", 1.0, (4, 4)), ("tpu-b", 2.0, (4, 8))])
        assert enc.carve is not None
        verdict, executor = topo_solver.solve_carve_window(
            enc, topo_solver.CarveConfig(device_min_cells=0))
        assert executor in ("device-carve", "host-carve")
        assert np.array_equal(verdict, topo.host_carve(enc.carve))
        assert np.array_equal(verdict, topo.scalar_carve(enc))

    def test_probe_sabotage_heals_to_scalar(self):
        enc, _, _ = _window(
            [("g0", 2, (2, 2), "default")], [("tpu-a", 1.0, (4, 4))])
        want = topo.scalar_carve(enc)
        before = _count(FILTER_FALLBACK_TOTAL, reason="carve-mismatch")
        ok, healed = topo_solver.check_probes(enc, ~want, probes=8)
        assert not ok
        assert np.array_equal(healed, want)
        assert _count(FILTER_FALLBACK_TOTAL,
                      reason="carve-mismatch") == before + 1

    def test_gang_window_self_heals_on_sabotaged_carve(self, monkeypatch):
        """Invert the device carve verdict mid-dispatch: the fetch probes
        condemn BOTH the carve and the gang verdicts, the fallback counter
        increments, and the plan is node-for-node the pure host plan."""
        specs = [("g0", 2, (2, 2), "default"), ("g1", 2, (2, 4), "default")]
        types = [("tpu-a", 1.0, (4, 4))]
        enc_ref, _, _ = _window(specs, types)
        ref = plan_gang_window(enc_ref)

        real = gang_solver._carve_jit

        def sabotaged(*shape):
            fn = real(*shape)

            def evil(occ, cls_of, scls_of, pmask, pvalid):
                return ~fn(occ, cls_of, scls_of, pmask, pvalid)

            return evil

        monkeypatch.setattr(gang_solver, "_carve_jit", sabotaged)
        before = _count(FILTER_FALLBACK_TOTAL, reason="carve-mismatch")
        enc, _, _ = _window(specs, types)
        feas, slots, executor = solve_gang_window(
            enc, GangConfig(device_min_cells=0, device_timeout_s=30.0))
        assert executor == "host-gang"  # device verdict condemned
        assert _count(FILTER_FALLBACK_TOTAL,
                      reason="carve-mismatch") == before + 1
        plan = plan_gang_window(enc, feas)

        def sig(pl):
            return [(p.gang.key,
                     [(bi, [q.metadata.name for q in qs])
                      for bi, qs in p.node_sets])
                    for p in pl.placements]

        assert sig(plan) == sig(ref)


class TestPhantomCapacityRegression:
    """The bug this PR fixes: shape-only resource math hands a slice gang
    a torus whose free chips are NOT contiguous."""

    def _fragmented_occ(self):
        # 8 free chips on a 4x4 torus, checkerboarded: resources for a
        # 2x2 gang fit, chips do not
        return np.array([(r + c) % 2 == 0 for r in range(4)
                         for c in range(4)], bool)

    def test_pre_fix_misplacement_pinned_shape_only(self):
        """With carving OFF (no annotations), the walk happily places a
        2x2-slice gang on the fragmented node — the pinned phantom."""
        probe = _pod("probe")
        big = [max(v, 1) * 100 for v in _reserve_vec(probe)]
        pods = [_pod("ph-m0"), _pod("ph-m1")]
        enc = encode_gang_window(
            [("ph", pods, np.ones(1, bool), None)], [list(big)], [1.0],
            ["tpu-a"],
            seed_bins=[_seed("frag-node", 0, big, None, [])])
        plan = plan_gang_window(enc)
        assert len(plan.placements) == 1
        assert plan.placements[0].node_sets[0][0] == 0  # the phantom bin

    def test_carve_walk_rejects_phantom_and_goes_fresh(self):
        rejects0 = _count(TOPOLOGY_CARVE_REJECTS_TOTAL)
        probe = _pod("probe")
        big = [max(v, 1) * 100 for v in _reserve_vec(probe)]
        seed = _seed("frag-node", 0, big, (4, 4), self._fragmented_occ())
        pods = [_pod("ph-m0"), _pod("ph-m1")]
        enc = encode_gang_window(
            [("ph", pods, np.ones(1, bool), None)], [list(big)], [1.0],
            ["tpu-a"], slices=[(2, 2)], bands=["default"],
            type_grids=[(4, 4)], seed_bins=[seed])
        plan = plan_gang_window(enc)
        assert len(plan.placements) == 1
        placed_bins = {bi for bi, _ in plan.placements[0].node_sets}
        assert 0 not in placed_bins  # phantom bin refused
        assert _count(TOPOLOGY_CARVE_REJECTS_TOTAL) > rejects0
        assert plan.placements[0].carves  # fresh bin carved instead

    def test_carve_reject_counted_once_per_bin_per_walk(self):
        """Every member's first-fit walk crosses the fragmented seed, but
        the reject is memoized within the walk: the counter prices
        rejected BINS, not members x bins."""
        rejects0 = _count(TOPOLOGY_CARVE_REJECTS_TOTAL)
        probe = _pod("probe")
        big = [max(v, 1) * 100 for v in _reserve_vec(probe)]
        seed = _seed("frag-node", 0, big, (4, 4), self._fragmented_occ())
        pods = [_pod(f"memo-m{i}") for i in range(3)]
        enc = encode_gang_window(
            [("memo", pods, np.ones(1, bool), None)], [list(big)], [1.0],
            ["tpu-a"], slices=[(2, 2)], bands=["default"],
            type_grids=[(4, 4)], seed_bins=[seed])
        plan = plan_gang_window(enc)
        assert len(plan.placements) == 1
        assert _count(TOPOLOGY_CARVE_REJECTS_TOTAL) == rejects0 + 1

    def test_two_gangs_split_when_one_torus_cannot_hold_both(self):
        """Two 4x4-slice gangs: resource math alone stacks both on bin 0;
        carve-aware placement gives each its own torus."""
        enc, _, _ = _window(
            [("a", 2, (4, 4), "default"), ("b", 2, (4, 4), "default")],
            [("tpu-a", 1.0, (4, 4))])
        plan = plan_gang_window(enc)
        assert len(plan.placements) == 2
        bins_a = {bi for bi, _ in plan.placements[0].node_sets}
        bins_b = {bi for bi, _ in plan.placements[1].node_sets}
        assert bins_a.isdisjoint(bins_b)


class TestKillSwitchParity:
    def test_carve_enabled_env(self, monkeypatch):
        monkeypatch.delenv(topo_solver._ENV, raising=False)
        assert topo_solver.carve_enabled()
        for off in ("0", "false", "OFF"):
            monkeypatch.setenv(topo_solver._ENV, off)
            assert not topo_solver.carve_enabled()

    def test_encoder_is_bit_for_bit_without_annotations(self):
        """The switch works by the controller passing NO annotations —
        pin that an annotation-free encode equals the legacy call shape
        on every tensor, with no carve side-car attached."""
        probe = _pod("probe")
        big = [max(v, 1) * 100 for v in _reserve_vec(probe)]
        pods = [_pod("kp-m0"), _pod("kp-m1")]
        gangs = [("kp", list(pods), np.ones(1, bool), None)]
        a = encode_gang_window(gangs, [list(big)], [1.0], ["tpu-a"])
        b = encode_gang_window(gangs, [list(big)], [1.0], ["tpu-a"],
                               slices=None, bands=None, type_grids=None,
                               seed_bins=None)
        assert a.carve is None and b.carve is None
        assert np.array_equal(a.compat, b.compat)
        assert a.b == b.b and a.g == b.g
        assert [bn.name for bn in a.bins] == [bn.name for bn in b.bins]
        if a.d_compat is not None or b.d_compat is not None:
            assert np.array_equal(a.d_compat, b.d_compat)

    def test_worker_passes_no_annotations_when_off(self, monkeypatch):
        monkeypatch.setenv(topo_solver._ENV, "0")
        topo.LEDGER.reset()
        kube = KubeCore()
        provider = FakeCloudProvider(catalog=tpu_catalog())
        provisioning = ProvisioningController(
            kube, provider,
            batcher_factory=lambda: Batcher(idle_seconds=0.05,
                                            max_seconds=2.0))
        selection = SelectionController(kube, provisioning,
                                        gate_timeout=30.0)
        p = make_provisioner()
        kube.create(p)
        provisioning.reconcile(p.metadata.name)
        try:
            pods = [_gang_pod("offg", 2, i, slice_="v5e-2x2")
                    for i in range(2)]
            expect_provisioned(kube, selection, provisioning, pods)
            for pod in pods:
                expect_scheduled(kube, pod)
            # switch off: nothing ever reaches the ledger
            assert topo.LEDGER.node_count() == 0
        finally:
            for w in provisioning.workers.values():
                w.stop()


class TestOccupancyLedger:
    def test_commit_release_prune_roundtrip(self):
        led = topo.OccupancyLedger()
        led.commit("n1", (4, 4), "tpu-a", (), ("ns", "g1"), [0, 1, 4, 5],
                   "default", [("ns", "p0")])
        led.commit("n2", (4, 4), "tpu-a", (), ("ns", "g2"), [0, 1],
                   "low", [("ns", "p1")])
        assert led.node_count() == 2
        snap = led.snapshot()
        assert {ng.node for ng in snap} == {"n1", "n2"}
        # snapshot is isolated: mutating it never reaches the ledger
        snap[0].occ[:] = False
        assert int(led.snapshot()[0].occ.sum()) in (2, 4)
        assert led.release_gang(("ns", "g1")) == ["n1"]
        assert led.node_count() == 1  # empty node dropped out
        led.prune(["some-other-node"])
        assert led.node_count() == 0

    def test_commit_is_idempotent_per_gang(self):
        led = topo.OccupancyLedger()
        led.commit("n1", (2, 2), "t", (), "g", [0, 1], "default", [])
        led.commit("n1", (2, 2), "t", (), "g", [0, 1], "default", [])
        ng = led.snapshot()[0]
        assert int(ng.occ.sum()) == 2
        assert len(ng.carves) == 1


class TestPricedPreemption:
    def _saturated_seed(self, big):
        return _seed("node-a", 0, [v // 100 for v in big], (4, 4),
                     np.ones(16, bool))

    def _ctx(self, band="low", cost=0.3, refund=None, big=None):
        refund = refund or [v for v in big]
        return PreemptContext([PreemptCandidate(
            gang_key=("d", "lo"), bin_index=0, node="node-a", band=band,
            pods=[("d", "lo-m0"), ("d", "lo-m1")],
            cells=np.arange(16), refund=list(refund),
            displacement_cost=cost)])

    def test_preempts_when_displacement_under_fresh_cost(self):
        pre0 = _count(PREEMPTIONS_TOTAL, band="low")
        _, _, big = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))])
        enc, _, _ = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))],
                            seed_bins=[self._saturated_seed(big)])
        plan = plan_gang_window(enc, preempt=self._ctx(cost=0.3, big=big))
        assert len(plan.placements) == 1
        assert plan.preemptions and plan.preemptions[0][1].node == "node-a"
        # the beneficiary landed on the freed seed bin, not a fresh node
        assert {bi for bi, _ in plan.placements[0].node_sets} == {0}
        # the PLANNER never counts executions — the controller does
        assert _count(PREEMPTIONS_TOTAL, band="low") == pre0

    def test_declines_when_fresh_is_cheaper(self):
        d0 = _count(PREEMPTION_DECLINED_TOTAL, reason="fresh-cheaper")
        _, _, big = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))])
        enc, _, big = _window([("hi", 2, (2, 2), "high")],
                              [("tpu-a", 1.0, (4, 4))],
                              seed_bins=[self._saturated_seed(big)])
        plan = plan_gang_window(enc, preempt=self._ctx(cost=1.5, big=big))
        assert not plan.preemptions
        assert _count(PREEMPTION_DECLINED_TOTAL,
                      reason="fresh-cheaper") == d0 + 1
        # fresh growth still places the gang (grow=True window)
        assert len(plan.placements) == 1
        assert {bi for bi, _ in plan.placements[0].node_sets} != {0}

    @pytest.mark.parametrize("band", ["system-critical", "high"])
    def test_never_displaces_equal_or_higher_band(self, band):
        d0 = _count(PREEMPTION_DECLINED_TOTAL, reason="no-victim")
        _, _, big = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))])
        enc, _, big = _window([("hi", 2, (2, 2), "high")],
                              [("tpu-a", 1.0, (4, 4))],
                              seed_bins=[self._saturated_seed(big)])
        plan = plan_gang_window(enc,
                                preempt=self._ctx(band=band, big=big))
        assert not plan.preemptions
        assert _count(PREEMPTION_DECLINED_TOTAL,
                      reason="no-victim") == d0 + 1

    def test_rollback_when_eviction_does_not_help(self):
        """Victim's refund is too small for the gang's members: evictions
        roll back, pool state untouched, candidate reusable."""
        d0 = _count(PREEMPTION_DECLINED_TOTAL, reason="unplaceable")
        _, _, big = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))])
        seed = self._saturated_seed(big)
        enc, _, _ = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))], seed_bins=[seed],
                            grow=False)
        ctx = self._ctx(cost=0.1, refund=[0] * len(big), big=big)
        free_before = list(enc.bins[0].free)
        plan = plan_gang_window(enc, preempt=ctx)
        assert not plan.placements and not plan.preemptions
        assert _count(PREEMPTION_DECLINED_TOTAL,
                      reason="unplaceable") == d0 + 1
        assert enc.bins[0].free == free_before
        assert not ctx.candidates[0].taken

    def test_rollback_restores_shared_bin_snapshots_newest_first(self):
        """Two victims on ONE bin: the second undo snapshot already
        contains the first victim's refund and freed cells, so only a
        newest-first restore returns the bin to its true state
        (regression: forward-order restore left the first refund behind
        a failed attempt — phantom capacity for the rest of the window)."""
        d0 = _count(PREEMPTION_DECLINED_TOTAL, reason="unplaceable")
        _, unit, big = _window([("hi", 2, (2, 2), "high")],
                               [("tpu-a", 1.0, (4, 4))])
        seed = _seed("node-a", 0, big, (4, 4), np.ones(16, bool))
        enc, _, _ = _window([("hi", 2, (2, 2), "high")],
                            [("tpu-a", 1.0, (4, 4))],
                            seed_bins=[seed], grow=False)
        # victim cells free only row 0 plus two scattered chips: no
        # contiguous 2x2 ever appears, so both evictions happen and fail
        ctx = PreemptContext([
            PreemptCandidate(
                gang_key=("d", "a"), bin_index=0, node="node-a",
                band="low", pods=[("d", "a-m0")], cells=np.arange(4),
                refund=list(unit), displacement_cost=0.1),
            PreemptCandidate(
                gang_key=("d", "b"), bin_index=0, node="node-a",
                band="low", pods=[("d", "b-m0")],
                cells=np.array([5, 10]), refund=list(unit),
                displacement_cost=0.2),
        ])
        free_state = [list(bn.free) for bn in enc.bins]
        occ_state = [enc.bins[0].occ.copy()]
        free_before = [list(v) for v in free_state]
        plan = gang_solver.GangPlan()
        slots = gang_solver._attempt_preemption(
            enc, enc.gangs[0], free_state, occ_state, {}, ctx, plan)
        assert slots is None
        assert plan.verified == 2  # the walk reached the second snapshot
        assert _count(PREEMPTION_DECLINED_TOTAL,
                      reason="unplaceable") == d0 + 1
        assert free_state == free_before
        assert occ_state[0].all()
        assert not any(c.taken for c in ctx.candidates)

    def test_full_pool_preemption_spans_freed_seed_and_fresh(self):
        """A gang the full-pool walk rejects still gets a displacement
        attempt: its members may only fit by spanning the freed seed
        torus plus fresh growth (regression: it was declared 'capacity'
        unplaced without ever consulting the preempt context)."""
        probe = _pod("probe")
        unit = [max(v, 1) for v in _reserve_vec(probe)]
        seed = _seed("node-a", 0, unit, (4, 4), np.ones(16, bool))
        pods = [_pod("sp-m0"), _pod("sp-m1")]
        enc = encode_gang_window(
            [("sp", pods, np.ones(1, bool), None)], [list(unit)], [1.0],
            ["tpu-a"], slices=[(2, 2)], bands=["high"],
            type_grids=[(4, 4)], seed_bins=[seed])
        assert enc.b == 3  # seed + two grown one-member bins
        # another gang already consumed one fresh replica: the gang no
        # longer fits anywhere without the seed torus
        enc.bins[2].free = [0] * len(unit)
        ctx = PreemptContext([PreemptCandidate(
            gang_key=("d", "lo"), bin_index=0, node="node-a", band="low",
            pods=[("d", "lo-m0")], cells=np.arange(16),
            refund=[0] * len(unit), displacement_cost=0.1)])
        plan = plan_gang_window(enc, preempt=ctx)
        assert not plan.unplaced
        assert len(plan.placements) == 1
        assert plan.preemptions and plan.preemptions[0][1].node == "node-a"
        assert {bi for bi, _ in plan.placements[0].node_sets} == {0, 1}
        assert set(plan.placements[0].carves) == {0, 1}


class TestBatcherRequeueDisplaced:
    def test_atomic_and_shed_proof(self):
        b = Batcher(idle_seconds=10.0, max_seconds=10.0, max_depth=1)
        try:
            assert b.add("filler", key="filler") is not None  # depth full
            entries = [
                (f"m{i}", f"m{i}", "low", -5, (("g",), 2))
                for i in range(2)
            ]
            assert b.requeue_displaced(entries) == 2  # bypasses the bound
            assert b.contains("m0") and b.contains("m1")
        finally:
            b.stop()


def _gang_pod(gname, size, i, slice_=None, priority=None):
    pod = _pod(f"{gname}-m{i}", cpu="2", mem="1Gi")
    pod.metadata.labels[wellknown.POD_GROUP_LABEL] = gname
    pod.metadata.labels[wellknown.POD_GROUP_SIZE_LABEL] = str(size)
    if slice_ is not None:
        pod.metadata.labels[wellknown.POD_GROUP_SLICE_LABEL] = slice_
    if priority is not None:
        pod.spec.priority = priority
    return pod


def _harness():
    topo.LEDGER.reset()
    kube = KubeCore()
    provider = FakeCloudProvider(catalog=tpu_catalog())
    provisioning = ProvisioningController(
        kube, provider,
        batcher_factory=lambda: Batcher(idle_seconds=0.05, max_seconds=2.0))
    selection = SelectionController(kube, provisioning, gate_timeout=30.0)
    p = make_provisioner()
    kube.create(p)
    provisioning.reconcile(p.metadata.name)
    return kube, provider, provisioning, selection


class TestCarveE2E:
    def test_carve_commits_and_second_gang_reuses_seed(self):
        committed0 = _count(TOPOLOGY_CARVES_COMMITTED_TOTAL)
        kube, provider, provisioning, selection = _harness()
        try:
            pods = [_gang_pod("carver", 2, i, slice_="v5e-2x2")
                    for i in range(2)]
            expect_provisioned(kube, selection, provisioning, pods)
            nodes = {expect_scheduled(kube, pod) for pod in pods}
            assert len(nodes) == 1
            assert _count(TOPOLOGY_CARVES_COMMITTED_TOTAL) == committed0 + 1
            snap = topo.LEDGER.snapshot()
            assert [ng.node for ng in snap] == list(nodes)
            assert int(snap[0].occ.sum()) == 4  # one 2x2 carve
            # the second gang seeds the SAME node instead of a fresh one
            pods2 = [_gang_pod("carver2", 2, i, slice_="v5e-2x2")
                     for i in range(2)]
            expect_provisioned(kube, selection, provisioning, pods2)
            nodes2 = {expect_scheduled(kube, pod) for pod in pods2}
            assert nodes2 == nodes
            assert int(topo.LEDGER.snapshot()[0].occ.sum()) == 8
        finally:
            for w in provisioning.workers.values():
                w.stop()

    def test_refused_launch_displaces_no_victims(self, monkeypatch):
        """The beneficiary's launch is refused (provisioner gone) AFTER
        the planner chose preemption: no victim may be displaced for a
        gang that never binds (regression: eviction used to execute
        before _launch_gang could refuse)."""
        kube, provider, provisioning, selection = _harness()
        pre0 = _count(PREEMPTIONS_TOTAL, band="low")
        try:
            low = [_gang_pod("low-keep", 2, i, slice_="v5e-4x4",
                             priority=-5) for i in range(2)]
            expect_provisioned(kube, selection, provisioning, low)
            lnodes = {expect_scheduled(kube, pod) for pod in low}
            assert len(lnodes) == 1
            failed0 = _count(GANGS_UNPLACEABLE_TOTAL, reason="bind-failed")
            real_get = kube.get

            def provisioner_gone(kind, name, namespace=""):
                if kind == "Provisioner":
                    raise NotFound(f"Provisioner {name}")
                return real_get(kind, name, namespace)

            monkeypatch.setattr(kube, "get", provisioner_gone)
            high = [_gang_pod("high-refused", 2, i, slice_="v5e-2x2",
                              priority=10) for i in range(2)]
            expect_provisioned(kube, selection, provisioning, high)

            def refused():
                assert _count(GANGS_UNPLACEABLE_TOTAL,
                              reason="bind-failed") > failed0

            eventually(refused)
            # the resident low gang is untouched: still bound, no
            # preemption executed, ledger carve intact
            assert _count(PREEMPTIONS_TOTAL, band="low") == pre0
            for pod in low:
                assert expect_scheduled(kube, pod) in lnodes
            for pod in high:
                expect_not_scheduled(kube, pod)
            assert topo.LEDGER.node_count() == 1
        finally:
            for w in provisioning.workers.values():
                w.stop()

    def test_preemption_lifecycle_through_worker(self):
        kube, provider, provisioning, selection = _harness()
        pre0 = _count(PREEMPTIONS_TOTAL, band="low")
        disp0 = _count(PREEMPTION_DISPLACED_PODS_TOTAL)
        try:
            low = [_gang_pod("low-res", 2, i, slice_="v5e-4x4",
                             priority=-5) for i in range(2)]
            expect_provisioned(kube, selection, provisioning, low)
            lnodes = {expect_scheduled(kube, pod) for pod in low}
            assert len(lnodes) == 1
            # the high gang wants a 2x2 carve; the only seeded torus is
            # full; displacement (victims refit on free fleet) beats the
            # $4/h fresh node -> preempt
            high = [_gang_pod("high-pri", 2, i, slice_="v5e-2x2",
                              priority=10) for i in range(2)]
            expect_provisioned(kube, selection, provisioning, high)
            hnodes = {expect_scheduled(kube, pod) for pod in high}
            assert hnodes == lnodes
            assert _count(PREEMPTIONS_TOTAL, band="low") == pre0 + 1
            assert _count(PREEMPTION_DISPLACED_PODS_TOTAL) == disp0 + 2
            # the displaced gang requeues through the batcher and rebinds
            deadline = time.monotonic() + 20
            bound = []
            while time.monotonic() < deadline:
                bound = [kube.get("Pod", q.metadata.name,
                                  q.metadata.namespace).spec.node_name
                         for q in low]
                if all(bound):
                    break
                time.sleep(0.2)
            assert all(bound), "displaced gang never rebound"
            assert set(bound).isdisjoint(hnodes)
        finally:
            for w in provisioning.workers.values():
                w.stop()


class TestCarve3DE2E:
    """The real 3-D torus catalog type (tpu-v4-2x2x4) through the full
    carve -> ledger -> seed-reuse worker path, closing the ROADMAP tail
    "3-D grids are encoded and oracle-tested but no real 3-D catalog
    type exercises them end-to-end"."""

    def test_3d_carve_commits_and_second_gang_reuses_seed(self):
        committed0 = _count(TOPOLOGY_CARVES_COMMITTED_TOTAL)
        kube, provider, provisioning, selection = _harness()
        try:
            # a v4-family 2x2x2 cube only fits the 3-D 2x2x4 host (the
            # v5e 2-D grids are a different family)
            pods = [_gang_pod("cube", 2, i, slice_="v4-2x2x2")
                    for i in range(2)]
            expect_provisioned(kube, selection, provisioning, pods)
            nodes = {expect_scheduled(kube, pod) for pod in pods}
            assert len(nodes) == 1
            node = kube.get("Node", next(iter(nodes)), "")
            assert node.metadata.labels[
                wellknown.LABEL_INSTANCE_TYPE] == "tpu-v4-2x2x4"
            assert _count(TOPOLOGY_CARVES_COMMITTED_TOTAL) == committed0 + 1
            snap = topo.LEDGER.snapshot()
            assert [ng.node for ng in snap] == list(nodes)
            assert snap[0].dims == (2, 2, 4)
            assert int(snap[0].occ.sum()) == 8  # one 2x2x2 cube
            # the second cube fills the REMAINING half of the same torus
            # instead of launching a fresh $6/h host
            pods2 = [_gang_pod("cube2", 2, i, slice_="v4-2x2x2")
                     for i in range(2)]
            expect_provisioned(kube, selection, provisioning, pods2)
            nodes2 = {expect_scheduled(kube, pod) for pod in pods2}
            assert nodes2 == nodes
            assert int(topo.LEDGER.snapshot()[0].occ.sum()) == 16
        finally:
            for w in provisioning.workers.values():
                w.stop()


class TestTerminationReleasesLedger:
    """Regression (ISSUE 19 satellite): a drained/GC'd carved node must
    stop being offered as a seed bin — the termination finalizer pops the
    node's ledger carves and folds their durable intents."""

    def test_terminate_pops_ledger_and_closes_carve_intent(self, tmp_path):
        from karpenter_tpu.api.core import Node, ObjectMeta
        from karpenter_tpu.controllers.termination import Terminator
        from karpenter_tpu.runtime.journal import IntentJournal

        topo.LEDGER.reset()
        kube = KubeCore()
        provider = FakeCloudProvider(catalog=tpu_catalog())
        journal = IntentJournal(str(tmp_path), fsync=False)
        node = Node(metadata=ObjectMeta(
            name="carved-n1", namespace="",
            finalizers=[wellknown.TERMINATION_FINALIZER]))
        kube.create(node)
        node = kube.get("Node", "carved-n1", "")
        cid = journal.open_intent(
            "carve", gang="ns/g1", node="carved-n1", grid=[4, 4],
            type="tpu-v5e-4x4", sig=[[], []], cells=[0, 1, 4, 5],
            band="default", pods=["ns/p0"])
        topo.LEDGER.commit("carved-n1", (4, 4), "tpu-v5e-4x4", ((), ()),
                           "ns/g1", [0, 1, 4, 5], "default", [("ns", "p0")],
                           intent_id=cid)
        assert topo.LEDGER.node_count() == 1
        term = Terminator(kube, provider, journal=journal)
        try:
            term.terminate(node)
        finally:
            term.eviction_queue.stop()
        assert topo.LEDGER.node_count() == 0
        assert cid not in journal.open_intents()
