"""Type-axis SPMD kernel ≡ single-device kernel ≡ host oracle.

The type-sharded path makes its per-node decisions through pmax/psum/pmin
collectives (parallel/type_sharded.py); these tests pin bit-identical
behavior on the virtual 8-device CPU mesh, including the record stream
(chosen/q/packed), not just node counts.
"""

import numpy as np
import pytest

from karpenter_tpu.cloudprovider.fake.provider import instance_types
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.models.ffd import device_args
from karpenter_tpu.ops.encode import encode
from karpenter_tpu.ops.pack import pack_chunk_flat, unpack_flat
from karpenter_tpu.parallel.type_sharded import (
    pack_chunk_type_sharded, type_mesh,
)
from karpenter_tpu.solver import host_ffd
from karpenter_tpu.solver.adapter import build_packables, pod_vectors
from tests.conftest import cpu_mesh_devices
from tests.expectations import unschedulable_pod

L = 32


def _encoded(pods, catalog):
    constraints = universe_constraints(catalog)
    packables, _ = build_packables(catalog, constraints, pods, [])
    vecs = pod_vectors(pods)
    ids = list(range(len(pods)))
    enc = encode(vecs, ids, packables)
    assert enc is not None
    return enc, vecs, ids, packables


def _run_both(enc, n_devices=8):
    mesh = type_mesh(cpu_mesh_devices(n_devices))
    args = device_args(enc)
    sharded = np.asarray(pack_chunk_type_sharded(*args, num_iters=L, mesh=mesh))
    single = np.asarray(pack_chunk_flat(*args, num_iters=L))
    S = enc.shapes.shape[0]
    return unpack_flat(sharded, S, L), unpack_flat(single, S, L)


class TestTypeShardedParity:
    @pytest.mark.parametrize("n_types,n_pods", [(8, 60), (16, 250), (24, 400)])
    def test_record_stream_identical(self, n_types, n_pods):
        catalog = instance_types(n_types)
        pods = [unschedulable_pod(requests={
            "cpu": f"{(i % 7 + 1) * 250}m",
            "memory": f"{(i % 5 + 1) * 256}Mi"}) for i in range(n_pods)]
        enc, _, _, _ = _encoded(pods, catalog)
        (c_s, d_s, done_s, ch_s, q_s, p_s), (c_1, d_1, done_1, ch_1, q_1, p_1) = (
            _run_both(enc))
        assert done_s == done_1
        np.testing.assert_array_equal(c_s, c_1)
        np.testing.assert_array_equal(d_s, d_1)
        np.testing.assert_array_equal(ch_s, ch_1)
        np.testing.assert_array_equal(q_s, q_1)
        np.testing.assert_array_equal(p_s, p_1)

    def test_node_count_matches_oracle(self):
        catalog = instance_types(16)
        pods = [unschedulable_pod(requests={
            "cpu": f"{(i % 4 + 1) * 500}m",
            "memory": f"{(i % 3 + 1) * 512}Mi"}) for i in range(300)]
        enc, vecs, ids, packables = _encoded(pods, catalog)
        (_, _, done, _, q, _), _ = _run_both(enc)
        assert done
        oracle = host_ffd.pack(vecs, ids, packables)
        assert int(q[q > 0].sum()) == oracle.node_count

    def test_unschedulable_drops_match(self):
        # one pod too large for every type: the sharded drop path must agree
        catalog = instance_types(8)
        pods = [unschedulable_pod(requests={"cpu": "500", "memory": "1Ti"}),
                unschedulable_pod(requests={"cpu": "1", "memory": "512Mi"})]
        enc, _, _, _ = _encoded(pods, catalog)
        (_, d_s, done_s, _, _, _), (_, d_1, done_1, _, _, _) = _run_both(enc)
        assert done_s == done_1
        np.testing.assert_array_equal(d_s, d_1)
        assert d_s.sum() == 1

    def test_mesh_size_must_divide_types(self):
        catalog = instance_types(8)  # pads to an 8-bucket; 8 % 3 != 0
        pods = [unschedulable_pod()]
        enc, _, _, _ = _encoded(pods, catalog)
        mesh = type_mesh(cpu_mesh_devices(3))
        with pytest.raises(AssertionError):
            pack_chunk_type_sharded(*device_args(enc), num_iters=4, mesh=mesh)


class TestTypeSpmdSolvePath:
    """The type-SPMD kernel as a first-class routed executor: selectable
    via SolverConfig(device_kernel='type-spmd') through the public solve()
    and solve_ffd_device, with chunk resume — not just a raw kernel."""

    def _problem(self, n_pods=120, n_types=16):
        catalog = instance_types(n_types)
        constraints = universe_constraints(catalog)
        pods = [unschedulable_pod(
            requests={"cpu": f"{100 + 37 * (i % 9)}m",
                      "memory": f"{64 * (1 + i % 5)}Mi"})
            for i in range(n_pods)]
        return catalog, constraints, pods

    def test_solve_ffd_device_type_spmd_matches_host(self):
        from karpenter_tpu.models.ffd import solve_ffd_device
        from karpenter_tpu.solver.adapter import build_packables

        catalog, constraints, pods = self._problem()
        packables, _ = build_packables(catalog, constraints, pods, [])
        vecs, ids = pod_vectors(pods), list(range(len(pods)))
        want = host_ffd.pack(vecs, ids, packables)
        got = solve_ffd_device(vecs, ids, packables, kernel="type-spmd")
        assert got is not None
        key = lambda r: (r.node_count, sorted(r.unschedulable),
                         sorted((tuple(p.instance_type_indices),
                                 p.node_quantity) for p in r.packings))
        assert key(got) == key(want)

    def test_chunk_resume(self):
        from karpenter_tpu.models.ffd import solve_ffd_device
        from karpenter_tpu.solver.adapter import build_packables

        catalog, constraints, pods = self._problem(n_pods=90)
        packables, _ = build_packables(catalog, constraints, pods, [])
        vecs, ids = pod_vectors(pods), list(range(len(pods)))
        want = host_ffd.pack(vecs, ids, packables)
        got = solve_ffd_device(vecs, ids, packables, kernel="type-spmd",
                               chunk_iters=2)  # force many resumes
        assert got is not None and got.node_count == want.node_count

    def test_public_solve_routes_type_spmd(self):
        from karpenter_tpu.solver.solve import SolverConfig, solve

        catalog, constraints, pods = self._problem()
        got = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_kernel="type-spmd"))
        want = solve(constraints, pods, catalog,
                     config=SolverConfig(use_device=False))
        assert got.node_count == want.node_count
        assert not got.unschedulable

    def test_cost_tiebreak_in_kernel(self):
        """The in-kernel cost tie-break runs INSIDE the type-sharded kernel
        (one extra pmin per node decision) — no demotion to the XLA scan —
        and must produce the identical cost-ordered packing."""
        from karpenter_tpu.solver.solve import SolverConfig, solve

        catalog, constraints, pods = self._problem()
        # DESCENDING prices invert the default first-tie order, so the
        # cost-tiebreak result provably differs from the no-cost result —
        # otherwise this test passes even with the tie-break deleted
        for i, it in enumerate(catalog):
            it.price = 0.1 * (len(catalog) - i)
        key = lambda r: sorted(
            (tuple(it.name for it in p.instance_type_options),
             p.node_quantity) for p in r.packings)
        want = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_kernel="xla", cost_tiebreak=True))
        plain = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_kernel="xla", cost_tiebreak=False))
        assert key(want) != key(plain), (
            "precondition: tiebreak must change the packing for this "
            "problem, or the equivalence check below is vacuous")
        got = solve(constraints, pods, catalog, config=SolverConfig(
            device_min_pods=1, device_kernel="type-spmd",
            cost_tiebreak=True))
        assert key(got) == key(want)

    def test_cost_tiebreak_record_stream_identical(self):
        """Raw-kernel differential in cost mode: the sharded kernel's full
        record stream (chosen/q/packed) must match the single-device XLA
        scan bit-for-bit when both apply the same price vector."""
        from karpenter_tpu.models.ffd import encode_prices

        catalog = instance_types(16)
        for i, it in enumerate(catalog):
            it.price = 0.1 * (len(catalog) - i)  # descending: inverts ties
        pods = [unschedulable_pod(requests={
            "cpu": f"{(i % 7 + 1) * 250}m",
            "memory": f"{(i % 5 + 1) * 256}Mi"}) for i in range(250)]
        constraints = universe_constraints(catalog)
        packables, sorted_types = build_packables(catalog, constraints,
                                                  pods, [])
        vecs = pod_vectors(pods)
        enc = encode(vecs, list(range(len(pods))), packables)
        assert enc is not None
        prices = encode_prices(
            [sorted_types[p.index].price for p in packables],
            enc.totals.shape[0])
        mesh = type_mesh(cpu_mesh_devices(8))
        args = device_args(enc)
        sharded = np.asarray(pack_chunk_type_sharded(
            *args, num_iters=L, mesh=mesh, prices=prices,
            cost_tiebreak=True))
        single = np.asarray(pack_chunk_flat(
            *args, num_iters=L, prices=prices, cost_tiebreak=True))
        np.testing.assert_array_equal(sharded, single)
