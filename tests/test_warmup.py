"""Compile warmup + persistent cache wiring (solver/warmup.py).

One tiny bucket keeps the test fast: the point is that the warmup drives
the SAME jitted entries the serving path uses (so a warmed bucket is a
compile-free bucket), never raises, and that the cache knob round-trips.
"""

from karpenter_tpu.solver import warmup
from karpenter_tpu.solver.solve import SolverConfig


class TestCompilationCache:
    def test_empty_dir_disables(self):
        assert warmup.configure_compilation_cache("") is False

    def test_configures_and_creates_dir(self, tmp_path):
        import jax

        cache = tmp_path / "xla-cache"
        old = jax.config.jax_compilation_cache_dir
        try:
            assert warmup.configure_compilation_cache(str(cache)) is True
            assert cache.is_dir()
            assert jax.config.jax_compilation_cache_dir == str(cache)
        finally:
            jax.config.update("jax_compilation_cache_dir", old)


class TestWarmupPass:
    def test_smallest_bucket_compiles_solo_and_batch(self):
        n = warmup.warmup_pass(SolverConfig(), shape_buckets=[8],
                               type_buckets=[8])
        assert n == 3  # one solo entry + one batch entry + the ring prebuild

    def test_ring_prebuild_leaves_warm_slot(self):
        from karpenter_tpu.solver import pipeline as pl

        pl.reset_ring()
        warmup.warmup_pass(SolverConfig(), shape_buckets=[8],
                           type_buckets=[8], include_solo=False)
        c1 = pl.get_ring().counters()
        assert c1["slots"] >= 1 and c1["allocations"] >= 1
        # a second pass over the same bucket must REFILL, not allocate
        warmup.warmup_pass(SolverConfig(), shape_buckets=[8],
                           type_buckets=[8], include_solo=False)
        c2 = pl.get_ring().counters()
        assert c2["allocations"] == c1["allocations"]
        assert c2["refills"] > c1["refills"]

    def test_failed_bucket_is_swallowed(self, monkeypatch):
        # force the synthetic builder to blow up: the pass must log and
        # return 0, never raise (warmup must never hurt boot)
        def boom(S, T):
            raise RuntimeError("synthetic failure")

        monkeypatch.setattr(warmup, "_synthetic_args", boom)
        assert warmup.warmup_pass(SolverConfig(), shape_buckets=[8],
                                  type_buckets=[8]) == 0

    def test_background_thread_completes(self):
        t = warmup.start_warmup(SolverConfig(), shape_buckets=[8],
                                type_buckets=[8], include_batch=False)
        t.join(timeout=120)
        assert not t.is_alive()
