"""Admission webhook server + manifest codec.

Reference: cmd/webhook/main.go (defaulting `/default-resource`, validation
`/validate-resource`) and the v1alpha5 CRD schema. Requests are genuine
admission.k8s.io/v1 AdmissionReviews over HTTP against a live server.
"""

import base64
import json
import threading
import urllib.request

import pytest

from karpenter_tpu.api.codec import provisioner_from_manifest, provisioner_to_manifest
from karpenter_tpu.api.core import NodeSelectorRequirement as Req
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.webhooks.server import serve

MANIFEST = {
    "apiVersion": "karpenter.sh/v1alpha5",
    "kind": "Provisioner",
    "metadata": {"name": "default"},
    "spec": {
        "labels": {"team": "ml"},
        "taints": [{"key": "dedicated", "value": "ml", "effect": "NoSchedule"}],
        "requirements": [
            {"key": "topology.kubernetes.io/zone", "operator": "In",
             "values": ["us-west-2a", "us-west-2b"]},
        ],
        "kubeletConfiguration": {"clusterDNS": ["10.0.0.10"]},
        "provider": {"instanceProfile": "karpenter-node"},
        "ttlSecondsAfterEmpty": 30,
        "ttlSecondsUntilExpired": 2592000,
        "limits": {"resources": {"cpu": "1000", "memory": "1000Gi"}},
    },
}


class StubProvider:
    """Minimal SPI surface for the webhook hooks."""

    def default(self, constraints):
        if constraints.requirements.capacity_types() is None:
            constraints.requirements = constraints.requirements.add(
                Req(key="karpenter.sh/capacity-type", operator="In",
                    values=["on-demand"]))

    def validate(self, constraints):
        if constraints.provider is not None and \
                not constraints.provider.get("instanceProfile"):
            return "provider.instanceProfile: required"
        return None


class TestCodec:
    def test_round_trip(self):
        p = provisioner_from_manifest(MANIFEST)
        assert p.metadata.name == "default"
        assert p.spec.constraints.labels == {"team": "ml"}
        assert p.spec.constraints.taints[0].key == "dedicated"
        assert p.spec.constraints.requirements.zones() == {
            "us-west-2a", "us-west-2b"}
        assert p.spec.constraints.provider == {"instanceProfile": "karpenter-node"}
        assert p.spec.ttl_seconds_after_empty == 30
        assert str(p.spec.limits.resources["cpu"]) == "1000"
        # status is always emitted, even empty — _merge's removal contract
        # ("owned fields always present") requires it (advisor r4); the
        # defaulting webhook's /spec-only patch filter keeps user manifests
        # untouched by this
        assert provisioner_to_manifest(p) == {
            **MANIFEST, "status": {"conditions": [], "resources": {}}}

    def test_empty_spec(self):
        p = provisioner_from_manifest({"metadata": {"name": "bare"}})
        assert p.spec.constraints.provider is None
        assert p.spec.limits.resources is None
        out = provisioner_to_manifest(p)
        assert out["spec"] == {}


@pytest.fixture()
def webhook():
    server = serve(port=0, cloud_provider=StubProvider())
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def post_review(base, path, obj, uid="test-uid"):
    review = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
              "request": {"uid": uid, "object": obj}}
    req = urllib.request.Request(
        base + path, data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


class TestWebhookServer:
    def test_healthz(self, webhook):
        with urllib.request.urlopen(webhook + "/healthz") as resp:
            assert resp.read() == b"ok"

    def test_defaulting_returns_jsonpatch(self, webhook):
        reply = post_review(webhook, "/default-resource", MANIFEST)
        response = reply["response"]
        assert response["uid"] == "test-uid"
        assert response["allowed"] is True
        patch = json.loads(base64.b64decode(response["patch"]))
        # the stub provider injected the capacity-type requirement
        added = [op for op in patch if "capacity-type" in json.dumps(op)]
        assert added and all(op["path"].startswith("/spec") for op in patch)

    def test_defaulting_noop_when_already_defaulted(self, webhook):
        p = provisioner_from_manifest(MANIFEST)
        StubProvider().default(p.spec.constraints)
        reply = post_review(webhook, "/default-resource",
                            provisioner_to_manifest(p))
        assert "patch" not in reply["response"]

    def test_validation_allows_good_manifest(self, webhook):
        reply = post_review(webhook, "/validate-resource", MANIFEST)
        assert reply["response"]["allowed"] is True

    def test_validation_denies_bad_operator(self, webhook):
        bad = json.loads(json.dumps(MANIFEST))
        bad["spec"]["requirements"][0]["operator"] = "Exists"
        reply = post_review(webhook, "/validate-resource", bad)
        assert reply["response"]["allowed"] is False
        assert "operator" in reply["response"]["status"]["message"]

    def test_validation_denies_restricted_label(self, webhook):
        bad = json.loads(json.dumps(MANIFEST))
        bad["spec"]["labels"] = {"karpenter.sh/provisioner-name": "x"}
        reply = post_review(webhook, "/validate-resource", bad)
        assert reply["response"]["allowed"] is False

    def test_validation_runs_provider_hook(self, webhook):
        bad = json.loads(json.dumps(MANIFEST))
        bad["spec"]["provider"] = {}
        reply = post_review(webhook, "/validate-resource", bad)
        assert reply["response"]["allowed"] is False
        assert "instanceProfile" in reply["response"]["status"]["message"]

    def test_defaulting_preserves_unmodeled_fields(self, webhook):
        """Fields the codec does not model (spec.weight, unknown kubelet
        keys) must never be removed by the defaulting patch."""
        extended = json.loads(json.dumps(MANIFEST))
        extended["spec"]["weight"] = 10
        extended["spec"]["kubeletConfiguration"]["containerRuntime"] = "containerd"
        reply = post_review(webhook, "/default-resource", extended)
        patch = json.loads(base64.b64decode(reply["response"]["patch"]))
        assert all(op["op"] != "remove" for op in patch)
        assert all("weight" not in op["path"] and
                   "containerRuntime" not in op["path"] for op in patch)

    def test_defaulting_does_not_reorder_requirement_values(self, webhook):
        unordered = json.loads(json.dumps(MANIFEST))
        unordered["spec"]["requirements"][0]["values"] = ["us-west-2b", "us-west-2a"]
        p = provisioner_from_manifest(unordered)
        StubProvider().default(p.spec.constraints)
        reply = post_review(webhook, "/default-resource",
                            provisioner_to_manifest(p))
        assert "patch" not in reply["response"]

    def test_handler_exception_echoes_request_uid(self, webhook):
        bad = json.loads(json.dumps(MANIFEST))
        bad["spec"]["limits"] = {"resources": {"cpu": "not-a-quantity"}}
        reply = post_review(webhook, "/default-resource", bad, uid="uid-42")
        assert reply["response"]["allowed"] is False
        assert reply["response"]["uid"] == "uid-42"

    def test_malformed_body_is_denied_not_crash(self, webhook):
        req = urllib.request.Request(
            webhook + "/default-resource", data=b"not json",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            reply = json.loads(resp.read())
        assert reply["response"]["allowed"] is False

    def test_unknown_path_404(self, webhook):
        req = urllib.request.Request(webhook + "/nope", data=b"{}")
        try:
            urllib.request.urlopen(req)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404


class TestConfigValidation:
    def test_valid_logging_config_allowed(self, webhook):
        cm = {"apiVersion": "v1", "kind": "ConfigMap",
              "metadata": {"name": "config-logging"},
              "data": {"zap-logger-config": '{"level": "info"}'}}
        reply = post_review(webhook, "/config-validation", cm)
        assert reply["response"]["allowed"] is True

    def test_bad_level_denied(self, webhook):
        cm = {"metadata": {"name": "config-logging"},
              "data": {"loglevel.solver": "shouty"}}
        reply = post_review(webhook, "/config-validation", cm)
        assert reply["response"]["allowed"] is False
        assert "shouty" in reply["response"]["status"]["message"]
