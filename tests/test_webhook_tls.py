"""Webhook TLS lifecycle: CA/serving-cert generation, Secret persistence
shared across replicas, HTTPS AdmissionReview round-trip, and serving-cert
rotation mid-flight with zero downtime (certs.py; reference counterpart:
cmd/webhook/main.go:49,57 knative certificates controller)."""

import base64
import datetime
import json
import ssl
import threading
import urllib.request

import pytest

# environment gate, not a failure: webhooks/certs.py generates X.509 via
# the `cryptography` package, which this image does not ship (and the
# no-new-deps build rule forbids installing). The suite previously died at
# collection (12 F/E); skipping keeps the TLS lifecycle covered wherever
# the dependency exists. Tracking: ROADMAP.md — runtime hardening.
pytest.importorskip(
    "cryptography",
    reason="'cryptography' not installed in this image; webhook TLS "
           "suite is environment-gated")

from karpenter_tpu.runtime.kubecore import KubeCore
from karpenter_tpu.webhooks import certs
from karpenter_tpu.webhooks.certs import (
    CertManager, cert_not_after, generate_ca, generate_serving_cert,
    inject_ca_bundle,
)
from karpenter_tpu.webhooks.server import serve


class TestCertGeneration:
    def test_ca_signs_serving_cert_with_sans(self):
        from cryptography import x509

        ca = generate_ca()
        pair = generate_serving_cert(
            ca, ["karpenter-webhook", "karpenter-webhook.karpenter.svc"])
        cert = x509.load_pem_x509_certificate(pair.cert_pem)
        ca_cert = x509.load_pem_x509_certificate(ca.cert_pem)
        assert cert.issuer == ca_cert.subject
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        assert set(sans.get_values_for_type(x509.DNSName)) == {
            "karpenter-webhook", "karpenter-webhook.karpenter.svc"}
        # the CA verifies its own signature chain
        ca_cert.public_key().verify(
            cert.signature, cert.tbs_certificate_bytes,
            __import__("cryptography.hazmat.primitives.asymmetric.ec",
                       fromlist=["ECDSA"]).ECDSA(
                cert.signature_hash_algorithm))

    def test_serving_cert_shorter_than_ca(self):
        ca = generate_ca()
        pair = generate_serving_cert(ca, ["x"])
        assert cert_not_after(pair.cert_pem) < cert_not_after(ca.cert_pem)


class TestCertManagerSecret:
    def test_persists_and_second_replica_loads(self):
        kube = KubeCore()
        m1 = CertManager(kube, namespace="karpenter")
        m1.ensure()
        secret = kube.get("Secret", certs.SECRET_NAME, "karpenter")
        assert set(secret.data) == {"ca.crt", "ca.key", "tls.crt", "tls.key"}
        assert secret.type == "kubernetes.io/tls"
        # a second manager (another replica) loads the SAME identity
        m2 = CertManager(kube, namespace="karpenter")
        m2.ensure()
        assert m2.serving.cert_pem == m1.serving.cert_pem
        assert m2.ca.cert_pem == m1.ca.cert_pem

    def test_near_expiry_reissues_keeping_ca(self):
        kube = KubeCore()
        m = CertManager(kube, namespace="karpenter")
        m.ensure()
        old_serving, old_ca = m.serving.cert_pem, m.ca.cert_pem
        # shrink lifetime below the margin by issuing a short-lived cert
        m.serving = generate_serving_cert(m.ca, m.dns_names, days=1)
        m._store()
        m2 = CertManager(kube, namespace="karpenter")
        m2.ensure()  # loads, sees near-expiry, re-issues under the same CA
        assert m2.ca.cert_pem == old_ca
        assert m2.serving.cert_pem != old_serving
        assert (cert_not_after(m2.serving.cert_pem)
                - datetime.datetime.now(datetime.timezone.utc)
                > m2.rotation_margin)

    def test_bootstrap_race_adopts_winner(self):
        """Two replicas bootstrapping concurrently must converge on ONE
        identity: the loser of the Secret create race adopts the winner's
        pair instead of patching its own over it."""
        kube = KubeCore()
        winner = CertManager(kube, namespace="karpenter")
        loser = CertManager(kube, namespace="karpenter")
        # both load nothing (simulating the race window), winner stores first
        winner.ensure()
        # loser minted its own pair before discovering the Secret exists
        loser.ca = generate_ca()
        loser.serving = generate_serving_cert(loser.ca, loser.dns_names)
        assert loser._store(adopt_on_conflict=True) is False
        assert loser.ca.cert_pem == winner.ca.cert_pem
        assert loser.serving.cert_pem == winner.serving.cert_pem
        # the stored Secret still holds the winner's pair
        stored = kube.get("Secret", certs.SECRET_NAME, "karpenter")
        assert base64.b64decode(stored.data["ca.crt"]) == winner.ca.cert_pem

    def test_ca_bundle_injection(self):
        ca = generate_ca()
        manifest = {"kind": "ValidatingWebhookConfiguration",
                    "webhooks": [{"name": "a", "clientConfig": {"service": {}}},
                                 {"name": "b"}]}
        out = inject_ca_bundle(manifest, ca.cert_pem)
        for hook in out["webhooks"]:
            assert base64.b64decode(hook["clientConfig"]["caBundle"]) == ca.cert_pem


@pytest.fixture()
def https_webhook():
    kube = KubeCore()
    manager = CertManager(kube, namespace="karpenter",
                          dns_names=["localhost"])
    manager.ensure()
    server = serve(port=0, cert_manager=manager, host="127.0.0.1")
    threading.Thread(target=server.serve_forever, daemon=True).start()
    port = server.socket.getsockname()[1]
    yield manager, port, kube
    server.shutdown()


def _post_review(port: int, ca_pem: bytes, path: str, review: dict) -> dict:
    import tempfile

    ctx = ssl.create_default_context()
    with tempfile.NamedTemporaryFile(suffix=".crt") as f:
        f.write(ca_pem)
        f.flush()
        ctx.load_verify_locations(f.name)
    req = urllib.request.Request(
        f"https://localhost:{port}{path}",
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
        return json.loads(resp.read())


def _peer_cert_serial(port: int, ca_pem: bytes) -> int:
    import socket
    import tempfile

    from cryptography import x509

    ctx = ssl.create_default_context()
    with tempfile.NamedTemporaryFile(suffix=".crt") as f:
        f.write(ca_pem)
        f.flush()
        ctx.load_verify_locations(f.name)
    with socket.create_connection(("localhost", port), timeout=10) as sock:
        with ctx.wrap_socket(sock, server_hostname="localhost") as tls:
            der = tls.getpeercert(binary_form=True)
    return x509.load_der_x509_certificate(der).serial_number


class TestHttpsAdmission:
    def test_https_roundtrip_defaulting(self, https_webhook):
        """The API server only dials HTTPS with a trusted caBundle — this
        is that call: CA-pinned client, AdmissionReview in, JSONPatch out."""
        manager, port, _ = https_webhook
        review = {"request": {"uid": "u-1", "object": {
            "apiVersion": "karpenter.sh/v1alpha5", "kind": "Provisioner",
            "metadata": {"name": "default"}, "spec": {}}}}
        reply = _post_review(port, manager.ca.cert_pem,
                             "/default-resource", review)
        assert reply["response"]["uid"] == "u-1"
        assert reply["response"]["allowed"] is True

    def test_untrusted_ca_is_rejected(self, https_webhook):
        manager, port, _ = https_webhook
        other_ca = generate_ca("imposter")
        with pytest.raises(Exception) as ei:
            _post_review(port, other_ca.cert_pem, "/default-resource",
                         {"request": {"uid": "u"}})
        assert "certificate" in str(ei.value).lower()

    def test_rotation_mid_flight(self, https_webhook):
        """Force the serving cert inside the rotation margin; the live
        server must present the NEW cert on the next handshake (same CA,
        same socket, no restart), and reviews keep working throughout."""
        manager, port, kube = https_webhook
        serial_before = _peer_cert_serial(port, manager.ca.cert_pem)
        # shrink remaining lifetime below the margin
        manager.serving = generate_serving_cert(manager.ca, manager.dns_names,
                                                days=1)
        manager._store()
        manager._reload_ctx()
        assert manager.rotate_if_needed() is True
        serial_after = _peer_cert_serial(port, manager.ca.cert_pem)
        assert serial_after != serial_before
        # rotated cert persisted for other replicas
        stored = kube.get("Secret", certs.SECRET_NAME, "karpenter")
        assert base64.b64decode(
            stored.data["tls.crt"]) == manager.serving.cert_pem
        # and admission still round-trips over the rotated cert
        reply = _post_review(port, manager.ca.cert_pem, "/validate-resource",
                             {"request": {"uid": "u-2", "object": {
                                 "apiVersion": "karpenter.sh/v1alpha5",
                                 "kind": "Provisioner",
                                 "metadata": {"name": "default"},
                                 "spec": {}}}})
        assert reply["response"]["uid"] == "u-2"

    def test_no_rotation_outside_margin(self, https_webhook):
        manager, _, _ = https_webhook
        assert manager.rotate_if_needed() is False


class TestCaBundleReconcile:
    def test_stamps_live_webhook_configurations(self):
        """certs.reconcile_ca_bundles patches the caBundle of the deployed
        (Mutating|Validating)WebhookConfiguration objects over raw API
        paths, skipping absent ones and avoiding no-op writes."""
        from karpenter_tpu.runtime.kubecore import NotFound as KNotFound
        from karpenter_tpu.webhooks.certs import (
            MUTATING_PATH, VALIDATING_PATH, reconcile_ca_bundles,
        )

        store = {
            MUTATING_PATH + "defaulting.webhook.karpenter.sh": {
                "metadata": {"name": "defaulting.webhook.karpenter.sh"},
                "webhooks": [{"name": "defaulting.webhook.karpenter.sh",
                              "clientConfig": {"service": {"name": "w"}}}],
            },
        }
        puts = []

        class RawClient:
            def get_raw(self, path):
                if path not in store:
                    raise KNotFound(path)
                return json.loads(json.dumps(store[path]))

            def put_raw(self, path, body):
                puts.append(path)
                store[path] = body
                return body

        ca = generate_ca()
        n = reconcile_ca_bundles(RawClient(), ca.cert_pem)
        assert n == 1  # validating config not applied yet → skipped
        stamped = store[MUTATING_PATH + "defaulting.webhook.karpenter.sh"]
        assert base64.b64decode(
            stamped["webhooks"][0]["clientConfig"]["caBundle"]) == ca.cert_pem
        # idempotent: second run sees the bundle already present, no PUT
        puts.clear()
        assert reconcile_ca_bundles(RawClient(), ca.cert_pem) == 1
        assert puts == []
