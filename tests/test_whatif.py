"""Differential suite for the batched what-if consolidation engine.

Seeded random fleets (seeds 1/7/42) pin the engine's three contracts:

- PARITY: the device kernel's (feasible, slots) equals the exact host
  mirror bit-for-bit — GCD scaling is exact division and receiver pruning
  only drops bins that can never be chosen, so the scaled int32 program IS
  the nano-int program.
- NEVER OVER-DRAIN: every action in a window plan replays cleanly as an
  independent place_onto commit sequence on a fresh bin set — the engine
  never drains a node whose pods don't fit on what actually survives.
- AT LEAST AS CHEAP: the one-window batched plan reclaims at least the
  $/h the old incremental removable_nodes pass would have.

Plus the relaxation backend's fallback contract: its plan is used only
when strictly cheaper AND fully feasible, else byte-for-byte the exact
FFD plan (solver/relax.py).
"""

import numpy as np
import pytest

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider.fake.provider import make_instance_type
from karpenter_tpu.controllers.provisioning import universe_constraints
from karpenter_tpu.models.consolidate import (
    node_bin, place_onto, removable_nodes, repack_plan, reschedulable_pods,
)
from karpenter_tpu.models.cost import CostConfig, effective_price
from karpenter_tpu.ops.whatif import encode_window, host_whatif
from karpenter_tpu.solver.whatif import (
    WhatIfConfig, plan_window, solve_window,
)

from tests.test_consolidation import priced_catalog, running_node, running_pod

SEEDS = (1, 7, 42)
FORCE_DEVICE = WhatIfConfig(device_min_cells=0)


def random_fleet(seed, n_nodes=12):
    """A seeded fleet over the priced catalog: mixed node sizes, 0-4 small
    pods each — enough slack that some drains are feasible, some not."""
    rng = np.random.RandomState(seed)
    catalog = priced_catalog()
    nodes, pods_by = [], {}
    for i in range(n_nodes):
        it = catalog[rng.randint(len(catalog))]
        node = running_node(f"n{i}", it)
        nodes.append(node)
        pods = []
        for j in range(rng.randint(5)):
            pods.append(running_pod(
                f"p{i}-{j}",
                cpu=f"{rng.choice([100, 250, 500, 1000])}m",
                memory=f"{rng.choice([64, 128, 256, 512])}Mi"))
        pods_by[node.metadata.name] = pods
    return catalog, nodes, pods_by


def window_of(nodes, pods_by, catalog):
    bins = [node_bin(n, pods_by[n.metadata.name]) for n in nodes]
    cand_idx, cand_movable, savings = [], [], []
    constraints = universe_constraints(catalog)
    by_type = {it.name: it for it in catalog}
    for i, n in enumerate(nodes):
        movable, ok = reschedulable_pods(pods_by[n.metadata.name])
        if not ok or not movable:
            continue
        cand_idx.append(i)
        cand_movable.append(movable)
        it = by_type[n.metadata.labels[wellknown.LABEL_INSTANCE_TYPE]]
        savings.append(effective_price(
            it, constraints.requirements, CostConfig())[0])
    return bins, cand_idx, cand_movable, savings


class TestWhatIfParity:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_device_matches_host_mirror(self, seed):
        catalog, nodes, pods_by = random_fleet(seed)
        bins, cand_idx, cand_movable, _ = window_of(nodes, pods_by, catalog)
        enc = encode_window(bins, cand_idx, cand_movable)
        assert enc.device_ready, "seeded fleets must be int32-encodable"
        feas, slots, executor = solve_window(enc, FORCE_DEVICE)
        assert executor == "device-whatif"
        host_feas, host_slots = host_whatif(enc)
        assert np.array_equal(feas, host_feas)
        assert np.array_equal(slots, host_slots)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_pruned_mirror_matches_unpruned_scan(self, seed):
        # host_whatif walks only the receiver-pruned bins; forcing the full
        # scan must give the identical answer (pruning is exact)
        catalog, nodes, pods_by = random_fleet(seed)
        bins, cand_idx, cand_movable, _ = window_of(nodes, pods_by, catalog)
        enc = encode_window(bins, cand_idx, cand_movable)
        pruned = host_whatif(enc)
        enc.kept = None
        full = host_whatif(enc)
        assert np.array_equal(pruned[0], full[0])
        assert np.array_equal(pruned[1], full[1])

    def test_unencodable_window_runs_host_executor(self):
        # coprime byte-level memory requests push the GCD to 1 and the
        # scaled column past int32 — the device tensors must be omitted
        # and the solve must still answer exactly, on host
        catalog = priced_catalog()
        nodes = [running_node(f"n{i}", catalog[2]) for i in range(2)]
        pods_by = {
            "n0": [running_pod("a", cpu="100m", memory="3")],
            "n1": [running_pod("b", cpu="100m", memory="7")],
        }
        bins, cand_idx, cand_movable, _ = window_of(nodes, pods_by, catalog)
        enc = encode_window(bins, cand_idx, cand_movable)
        assert not enc.device_ready
        feas, _, executor = solve_window(enc, FORCE_DEVICE)
        assert executor == "host-whatif"
        assert list(feas) == [True, True]


class TestWindowPlan:
    @pytest.mark.parametrize("seed", SEEDS)
    def test_every_drain_replays_on_fresh_bins(self, seed):
        catalog, nodes, pods_by = random_fleet(seed)
        bins, cand_idx, cand_movable, savings = window_of(
            nodes, pods_by, catalog)
        enc = encode_window(bins, cand_idx, cand_movable)
        feas, _, _ = solve_window(enc, FORCE_DEVICE)
        plan = plan_window(enc, feas, savings, max_drains=len(nodes))
        # independent replay: every executed drain must fit on what
        # actually survives, in plan order, on a FRESH bin set
        vbins = [node_bin(n, pods_by[n.metadata.name]) for n in nodes]
        drained = set()
        for action in plan.actions:
            movable = cand_movable[action.cand]
            surviving = [b for j, b in enumerate(vbins)
                         if j != action.bin and j not in drained]
            assert place_onto(movable, surviving, commit=True) is not None, \
                f"seed {seed}: drained bin {action.bin} does not replay"
            drained.add(action.bin)

    @pytest.mark.parametrize("seed", SEEDS)
    def test_batched_reclaims_at_least_incremental(self, seed):
        catalog, nodes, pods_by = random_fleet(seed)
        bins, cand_idx, cand_movable, savings = window_of(
            nodes, pods_by, catalog)
        enc = encode_window(bins, cand_idx, cand_movable)
        feas, _, _ = solve_window(enc, FORCE_DEVICE)
        # the incremental pass's receiver set: every unpinned node (empty
        # ones included), fewest movable pods first — what the controller
        # hands plan_window so its emulation leg matches removable_nodes
        targets = [i for _, i in sorted(
            (len(reschedulable_pods(pods_by[n.metadata.name])[0]), i)
            for i, n in enumerate(nodes))]
        plan = plan_window(enc, feas, savings, max_drains=len(nodes),
                           incremental_targets=targets)

        removed = removable_nodes(nodes, pods_by, max_actions=len(nodes))
        constraints = universe_constraints(catalog)
        by_type = {it.name: it for it in catalog}
        incremental = sum(
            effective_price(
                by_type[n.metadata.labels[wellknown.LABEL_INSTANCE_TYPE]],
                constraints.requirements, CostConfig())[0]
            for n in removed)
        assert plan.reclaimed_per_hour >= incremental - 1e-9


class TestRelaxContract:
    def test_relaxation_wins_when_cheaper_fleet_exists(self):
        # FFD minimizes node count → one big 8-cpu node ($0.90); the
        # relaxation sees four 2-cpu nodes cost $0.40 and must beat it
        catalog = [
            make_instance_type("small", cpu="2", memory="4Gi", pods="20",
                               price=0.10),
            make_instance_type("large", cpu="8", memory="16Gi", pods="80",
                               price=0.90),
        ]
        constraints = universe_constraints(catalog)
        nodes = [running_node(f"n{i}", catalog[1]) for i in range(4)]
        pods_by = {
            f"n{i}": [running_pod(f"p{i}-{j}", cpu="1", memory="512Mi")
                      for j in range(2)]
            for i in range(4)}
        plan = repack_plan(nodes, pods_by, constraints, catalog,
                           backend="relax")
        assert plan.relax is not None and plan.relax.used
        assert plan.relax.reason == "relaxation"
        assert plan.relax.relax_cost < plan.relax.ffd_cost
        assert plan.replacement.unschedulable == []
        assert plan.planned_cost_per_hour < 0.90

    @pytest.mark.parametrize("seed", SEEDS)
    def test_fallback_is_exact_ffd_parity(self, seed):
        # whatever the relaxation does on a seeded fleet, the emitted plan
        # is always feasible; on fallback it is the exact-FFD plan verbatim
        catalog, nodes, pods_by = random_fleet(seed, n_nodes=8)
        constraints = universe_constraints(catalog)
        relaxed = repack_plan(nodes, pods_by, constraints, catalog,
                              backend="relax")
        assert relaxed.replacement.unschedulable == []
        assert relaxed.relax is not None
        if relaxed.relax.used:
            assert relaxed.relax.relax_cost < relaxed.relax.ffd_cost
        else:
            exact = repack_plan(nodes, pods_by, constraints, catalog)
            assert relaxed.relax.reason.startswith("fallback-")
            assert relaxed.planned_nodes == exact.planned_nodes
            assert relaxed.planned_cost_per_hour == pytest.approx(
                exact.planned_cost_per_hour)
