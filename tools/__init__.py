"""Operator-facing CLI tools (verdict filters, bench history, replay
driver). A package so tests can import the verdict logic directly."""
