"""Soft-affinity verdict: one human-readable line from the bench JSON.

`make bench-affinity` pipes bench.py (``--only config_18``) through this
filter. The bench line passes through UNCHANGED on stdout (so
`> BENCH_rNN.json` redirects still capture the pure JSON); the verdict
goes to stderr:

    soft affinity: 24 cohorts x 400 types, co-location 2.0x vs soft-off \
(24/12) at 0.0% node regression, device soft scoring 51.3x vs per-cell \
host loop, row_divergence=0, unverified=0 — PASS

PASS needs (the round-16 acceptance gate):
- co-located cohorts >= 2x the KARPENTER_SOFT_AFFINITY=0 leg — the
  preferred-term votes actually steer follower launches onto their
  anchors' zones;
- node-count regression <= 1%: zone steering narrows offerings, it must
  never inflate the fleet;
- device soft scoring >= 5x the per-cell host loop computing the same
  exact-int algebra (micro-$ base + clamp(-w x scale), min over viable
  zones), with the probe re-verification timed INSIDE the device leg;
- zero row divergence between the device rows and the host loop, and
  zero unverified placements: no score-mismatch or
  soft-affinity-mismatch fallback fired anywhere in the run.
"""

from __future__ import annotations

import json
import sys

GATE_SPEEDUP = 5.0
GATE_COLOC = 2.0
GATE_REGRESSION_PCT = 1.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_18_soft_affinity", {})
    if "error" in cfg or "speedup" not in cfg:
        return ("soft affinity: no config_18_soft_affinity in bench line "
                f"({cfg.get('error', cfg.get('skipped', 'config_18 not run'))})"
                " — NO VERDICT")
    speedup = cfg.get("speedup")
    gain = cfg.get("coloc_gain")
    reg = cfg.get("node_regression_pct")
    div = cfg.get("row_divergence")
    unverified = cfg.get("unverified")
    head = (f"soft affinity: {cfg.get('cohorts')} cohorts x "
            f"{cfg.get('types')} types, co-location {gain}x vs soft-off "
            f"({cfg.get('coloc_on')}/{cfg.get('coloc_off')}) at {reg}% "
            f"node regression, device soft scoring {speedup}x vs per-cell "
            f"host loop, row_divergence={div}, unverified={unverified}")
    ok = (speedup is not None and speedup >= GATE_SPEEDUP
          and gain is not None and gain >= GATE_COLOC
          and reg is not None and reg <= GATE_REGRESSION_PCT
          and div == 0 and unverified == 0)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_COLOC}x co-location at <={GATE_REGRESSION_PCT}% "
            f"regression, >={GATE_SPEEDUP}x kernel, 0 divergence, "
            "0 unverified)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("soft affinity: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
