"""Render the BENCH_r*.json trajectory as one table (``make bench-history``).

Each round of work leaves one BENCH_rNN.json (the bench's single metric
line, possibly pretty-printed); some rounds also leave named variants
(BENCH_r04_builder.json, BENCH_r04_quiet.json, ...). This tool folds them
all into one chronological table so a reader can see how the headline and
the per-config extras moved across rounds without opening ten files:

    round  variant  metric                                   value unit  dev  configs
    r01    -        p99_solve_latency_ms_50k_pods_x_400_types 41.2 ms    1    1,4
    ...

Rows are sorted by round then variant; unparseable files are reported on
stderr and skipped, never fatal.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

_NAME = re.compile(r"BENCH_(r\d+)(?:_([A-Za-z0-9-]+))?\.json$")


def _config_ids(extra: dict) -> str:
    """Compressed list of the config slots present (and not skipped/errored):
    'config_7_control_plane_10k_pods' → '7'."""
    ids = []
    for key, val in extra.items():
        m = re.match(r"config_(\d+)", key)
        if not m or not isinstance(val, dict):
            continue
        if "skipped" in val:
            continue
        ids.append(m.group(1) + ("!" if "error" in val else ""))
    return ",".join(sorted(ids, key=lambda s: int(s.rstrip("!")))) or "-"


def _marshal_cell(extra: dict) -> str:
    """Compressed delta-marshal column (config_10, round 10+): speedup,
    steady-state fresh catalog transfers, window delta fraction —
    '3.98x/0xfer/d0.10'. '-' when the config never ran."""
    cfg = extra.get("config_10_marshal_delta")
    if not isinstance(cfg, dict) or "speedup" not in cfg:
        return "-"
    frac = cfg.get("delta_fraction")
    frac_s = f"/d{frac:.2f}" if isinstance(frac, (int, float)) else ""
    return (f"{cfg['speedup']}x/"
            f"{cfg.get('fresh_catalog_transfers', '?')}xfer{frac_s}")


def _gang_cell(extra: dict) -> str:
    """Compressed gang co-pack column (config_11, round 11+): speedup,
    parity (verdict AND node), placed/total gangs — '6.6x/par/256'.
    '!par' flags a parity break; '-' when the config never ran."""
    cfg = extra.get("config_11_gang_copack")
    if not isinstance(cfg, dict) or "speedup" not in cfg:
        return "-"
    par = "par" if (cfg.get("verdict_parity") and cfg.get("node_parity")) \
        else "!par"
    return f"{cfg['speedup']}x/{par}/{cfg.get('placed_gangs', '?')}"


def _filter_cell(extra: dict) -> str:
    """Compressed device-filter column (config_12, round 12+): speedup,
    verdict (zero divergence AND node parity), steady device allocations —
    '4.1x/par/a0'. '!par' flags any divergence; '-' when the config never
    ran."""
    cfg = extra.get("config_12_device_filter")
    if not isinstance(cfg, dict) or "speedup" not in cfg:
        return "-"
    par = "par" if (cfg.get("verdict_divergence") == 0
                    and cfg.get("node_parity")) else "!par"
    return f"{cfg['speedup']}x/{par}/a{cfg.get('steady_allocations', '?')}"


def _policy_cell(extra: dict) -> str:
    """Compressed policy-scoring column (config_13, round 13+): speedup,
    verdict (default-policy row parity AND node parity AND zero unverified
    AND the spot frontier holding), frontier points held — '37.5x/par/f7'.
    '!par' flags any break; '-' when the config never ran."""
    cfg = extra.get("config_13_policy_scoring")
    if not isinstance(cfg, dict) or "speedup" not in cfg:
        return "-"
    par = "par" if (cfg.get("row_divergence_default") == 0
                    and cfg.get("node_parity")
                    and cfg.get("unverified") == 0
                    and cfg.get("frontier_ok")) else "!par"
    return f"{cfg['speedup']}x/{par}/f{len(cfg.get('spot_frontier') or [])}"


def _global_cell(extra: dict) -> str:
    """Compressed global-window column (config_14, round 14+): fleet
    saving vs per-schedule FFD, accepted schedules, verdict (decline
    parity AND zero unverified AND live kill switch) — '12.48%/a3/par'.
    '!par' flags any break; '-' when the config never ran."""
    cfg = extra.get("config_14_global_window")
    if not isinstance(cfg, dict) or "saving_pct" not in cfg:
        return "-"
    par = "par" if (cfg.get("decline_parity")
                    and cfg.get("unverified") == 0
                    and cfg.get("killswitch_gate")) else "!par"
    return f"{cfg['saving_pct']}%/a{cfg.get('accepted', '?')}/{par}"


def _slo_cell(extra: dict) -> str:
    """Compressed SLO column (config_9 replay + chaos probe, round 14+):
    clean-leg sentinel trips, chaos-probe trips, worst digest-parity
    relative error — 't0/c1/p0.58%'. '!' flags a clean-leg trip or broken
    parity; '-' when the SLO engine never reported."""
    cfg = extra.get("config_9_million_pod_replay")
    if not isinstance(cfg, dict):
        return "-"
    slo = (cfg.get("replay") or {}).get("slo") if isinstance(
        cfg.get("replay"), dict) else None
    if not isinstance(slo, dict):
        return "-"
    trips = slo.get("trips", "?")
    trip_s = f"t{trips}" + ("!" if trips not in (0, "?") else "")
    chaos = cfg.get("slo_chaos")
    chaos_s = (f"/c{chaos.get('trips', '?')}"
               if isinstance(chaos, dict) else "")
    parity = (cfg.get("replay") or {}).get("slo_digest_parity")
    parity_s = ""
    if isinstance(parity, dict):
        worst = max((e for band in parity.values() if isinstance(band, dict)
                     for e in band.values()), default=0.0)
        parity_s = (f"/p{worst * 100:.2f}%"
                    + ("" if parity.get("within_1pct") else "!"))
    return f"{trip_s}{chaos_s}{parity_s}"


def _from_tail(tail: str):
    """Best-effort recovery of the bench JSON line from a captured stdout
    tail: parse from the LAST '{"metric"' occurrence (the line is emitted
    last, so its suffix is always present; only a truncated head loses it)."""
    idx = tail.rfind('{"metric"')
    if idx < 0:
        return None
    for end in (None, tail.find("\n", idx)):
        chunk = tail[idx:end] if end and end > 0 else tail[idx:]
        try:
            line = json.loads(chunk.strip())
            if isinstance(line, dict) and "metric" in line:
                return line
        except ValueError:
            continue
    return None


def load_rows(root: str) -> list:
    rows, bad = [], []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _NAME.search(os.path.basename(path))
        if not m:
            continue
        rnd, variant = m.group(1), m.group(2) or "-"
        try:
            with open(path) as f:
                line = json.load(f)
        except (OSError, ValueError) as e:
            bad.append(f"{os.path.basename(path)}: {e}")
            continue
        if (isinstance(line, dict) and "metric" not in line
                and isinstance(line.get("line"), dict)):
            line = line["line"]  # {"cmd", "rc", "note", "line": {...}} wrapper
        if isinstance(line, dict) and "metric" not in line and "tail" in line:
            # early-round driver capture: {"n", "cmd", "rc", "tail"} with
            # the bench line embedded in (and possibly truncated at the
            # front of) the tail — recover it when its start survived
            inner = _from_tail(line.get("tail", ""))
            if inner is None:
                rows.append({
                    "round": rnd, "variant": variant,
                    "metric": f"(tail truncated, rc={line.get('rc')})",
                    "value": None, "unit": "", "device_count": None,
                    "backend": "?", "degraded": None, "configs": "-",
                    "marshal": "-", "gang": "-", "filter": "-",
                    "policy": "-", "global": "-", "slo": "-"})
                continue
            line = inner
        extra = line.get("extra", {}) if isinstance(line, dict) else {}
        rows.append({
            "round": rnd,
            "variant": variant,
            "metric": line.get("metric", "?"),
            "value": line.get("value"),
            "unit": line.get("unit", ""),
            "device_count": extra.get("device_count"),
            "backend": extra.get("backend", "?"),
            "degraded": extra.get("degraded"),
            "configs": _config_ids(extra),
            "marshal": _marshal_cell(extra),
            "gang": _gang_cell(extra),
            "filter": _filter_cell(extra),
            "policy": _policy_cell(extra),
            "global": _global_cell(extra),
            "slo": _slo_cell(extra),
        })
    for b in bad:
        print(f"bench-history: skipped {b}", file=sys.stderr)
    rows.sort(key=lambda r: (r["round"], r["variant"]))
    return rows


def render(rows: list) -> str:
    headers = ["round", "variant", "metric", "value", "unit",
               "device_count", "backend", "degraded", "configs", "marshal",
               "gang", "filter", "policy", "global", "slo"]
    table = [headers] + [
        ["" if r[h] is None else str(r[h]) for h in headers] for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    lines = []
    for n, row in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
        if n == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or ["."])[0]
    rows = load_rows(root)
    if not rows:
        print(f"bench-history: no BENCH_r*.json under {root}", file=sys.stderr)
        return 1
    print(render(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
