"""Perf-regression gate over the BENCH_r*.json trajectory
(``make bench-regress``).

Each round of work leaves one BENCH_rNN.json (plus named variants); the
rounds are sparse — every round runs a subset of the configs — so each
tracked series is the chronological list of rounds that actually measured
it. The gate compares each series' LATEST value against the BEST prior
value with a per-series tolerance (throughput may dip with host noise;
latency may wobble; a collapse fails):

    series                        n  best_prior  latest  verdict
    control_plane_pods_bound_s    7  3006        2642    ok (-12.1% <= 30%)
    ...
    bench-regress: 6 series checked, 0 regressions — PASS

Parity flags are ratchets, not tolerances: once a round reports gang
co-pack or device-filter parity, the latest round that reports it must
still hold it. Exit code 1 on any regression — CI-grade, pipe-friendly.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

_NAME = re.compile(r"BENCH_(r\d+)(?:_([A-Za-z0-9-]+))?\.json$")


def _from_tail(tail: str):
    """Recover the bench JSON line from a captured stdout tail (same
    best-effort contract as tools/bench_history.py)."""
    idx = tail.rfind('{"metric"')
    if idx < 0:
        return None
    for end in (None, tail.find("\n", idx)):
        chunk = tail[idx:end] if end and end > 0 else tail[idx:]
        try:
            line = json.loads(chunk.strip())
            if isinstance(line, dict) and "metric" in line:
                return line
        except ValueError:
            continue
    return None


def _dig(d, *path):
    for p in path:
        if not isinstance(d, dict):
            return None
        d = d.get(p)
    return d


# (name, extractor(line) -> float|None, direction, tolerance)
# direction "higher": latest >= (1 - tol) * best_prior
# direction "lower":  latest <= (1 + tol) * best_prior
# Tolerances are calibrated so the REAL trajectory passes (config_7
# throughput dipped 12% r08→r11 on host noise; the headline p99 and the
# replay p99 only ever improved) while a collapse — half the throughput,
# double the latency — fails.
SERIES = [
    ("headline_p99_ms",
     lambda l: l.get("value"), "lower", 0.50),
    ("control_plane_pods_bound_per_sec",
     lambda l: _dig(l, "extra", "config_7_control_plane_10k_pods",
                    "pods_bound_per_sec"), "higher", 0.30),
    ("replay_default_p99_s",
     lambda l: _dig(l, "extra", "config_9_million_pod_replay", "replay",
                    "pending_to_bound_s", "default", "p99"), "lower", 0.50),
    ("marshal_delta_speedup",
     lambda l: _dig(l, "extra", "config_10_marshal_delta", "speedup"),
     "higher", 0.30),
    ("gang_copack_speedup",
     lambda l: _dig(l, "extra", "config_11_gang_copack", "speedup"),
     "higher", 0.30),
    ("device_filter_speedup",
     lambda l: _dig(l, "extra", "config_12_device_filter", "speedup"),
     "higher", 0.30),
    ("policy_scoring_speedup",
     lambda l: _dig(l, "extra", "config_13_policy_scoring", "speedup"),
     "higher", 0.30),
    ("global_window_saving_pct",
     lambda l: _dig(l, "extra", "config_14_global_window", "saving_pct"),
     "higher", 0.30),
    # p99 over 16 sub-10ms replays: single-digit-ms walls jitter 2-4x on
    # host noise alone, so the tolerance is wide — a real regression
    # (an fsync leaking onto the replay path, a quadratic ledger scan)
    # lands 10x+ past the best prior and still fails
    ("recovery_time_p99_ms",
     lambda l: _dig(l, "extra", "config_15_crash_recovery", "recovery",
                    "wall_ms", "p99_ms"), "lower", 2.00),
    ("topology_carve_gain_pct",
     lambda l: _dig(l, "extra", "config_16_topology_carve", "gain_pct"),
     "higher", 0.30),
    # sub-ms kernel walls against a ~100ms scalar loop: the ratio jitters
    # with host noise in the denominator, but a real regression (the
    # carve falling off the device path) drops it ~100x and still fails
    ("topology_carve_speedup",
     lambda l: _dig(l, "extra", "config_16_topology_carve", "speedup"),
     "higher", 0.80),
    # cold-ledger rebuild over the gang loop's open carve intents: the
    # same sub-10ms-wall jitter argument as recovery_time_p99_ms
    ("ledger_recovery_p99_ms",
     lambda l: _dig(l, "extra", "config_17_carve_journal", "recovery",
                    "wall_ms", "p99_ms"), "lower", 2.00),
    ("soft_affinity_coloc_gain",
     lambda l: _dig(l, "extra", "config_18_soft_affinity", "coloc_gain"),
     "higher", 0.30),
    ("soft_affinity_speedup",
     lambda l: _dig(l, "extra", "config_18_soft_affinity", "speedup"),
     "higher", 0.30),
]

# (name, extractor(line) -> bool|None): latest non-None entry must be True
FLAGS = [
    ("gang_copack_parity",
     lambda l: (None if _dig(l, "extra", "config_11_gang_copack",
                             "verdict_parity") is None
                else bool(_dig(l, "extra", "config_11_gang_copack",
                               "verdict_parity"))
                and bool(_dig(l, "extra", "config_11_gang_copack",
                              "node_parity")))),
    ("device_filter_parity",
     lambda l: (None if _dig(l, "extra", "config_12_device_filter",
                             "verdict_divergence") is None
                else _dig(l, "extra", "config_12_device_filter",
                          "verdict_divergence") == 0
                and bool(_dig(l, "extra", "config_12_device_filter",
                              "node_parity")))),
    ("policy_scoring_parity",
     lambda l: (None if _dig(l, "extra", "config_13_policy_scoring",
                             "row_divergence_default") is None
                else _dig(l, "extra", "config_13_policy_scoring",
                          "row_divergence_default") == 0
                and bool(_dig(l, "extra", "config_13_policy_scoring",
                              "node_parity"))
                and _dig(l, "extra", "config_13_policy_scoring",
                         "unverified") == 0
                and bool(_dig(l, "extra", "config_13_policy_scoring",
                              "frontier_ok")))),
    ("global_window_parity",
     lambda l: (None if _dig(l, "extra", "config_14_global_window",
                             "decline_parity") is None
                else bool(_dig(l, "extra", "config_14_global_window",
                               "decline_parity"))
                and _dig(l, "extra", "config_14_global_window",
                         "unverified") == 0
                and bool(_dig(l, "extra", "config_14_global_window",
                              "killswitch_gate")))),
    ("slo_clean_trips_zero",
     lambda l: (None if _dig(l, "extra", "config_9_million_pod_replay",
                             "replay", "slo") is None
                else _dig(l, "extra", "config_9_million_pod_replay",
                          "replay", "slo", "trips") == 0)),
    ("slo_digest_parity",
     lambda l: (None if _dig(l, "extra", "config_9_million_pod_replay",
                             "replay", "slo_digest_parity") is None
                else bool(_dig(l, "extra", "config_9_million_pod_replay",
                               "replay", "slo_digest_parity",
                               "within_1pct")))),
    ("crash_recovery_clean",
     lambda l: (None if _dig(l, "extra", "config_15_crash_recovery",
                             "leaks") is None
                else _dig(l, "extra", "config_15_crash_recovery",
                          "leaks") == 0
                and _dig(l, "extra", "config_15_crash_recovery",
                         "open_intents_after") == 0
                and _dig(l, "extra", "config_15_crash_recovery",
                         "recovery", "errors") == 0
                and (_dig(l, "extra", "config_15_crash_recovery",
                          "journal_tax", "overhead_pct") or 0.0) <= 1.0)),
    ("topology_carve_clean",
     lambda l: (None if _dig(l, "extra", "config_16_topology_carve",
                             "unverified") is None
                else _dig(l, "extra", "config_16_topology_carve",
                          "unverified") == 0
                and _dig(l, "extra", "config_16_topology_carve",
                         "kernel_divergence") == 0
                and _dig(l, "extra", "config_16_topology_carve",
                         "system_critical_preemptions") == 0
                and bool(_dig(l, "extra", "config_16_topology_carve",
                              "killswitch_gate"))
                and bool(_dig(l, "extra", "config_16_topology_carve",
                              "killswitch_parity")))),
    # the durable-ledger contract: carve-journal tax within the 1% gate,
    # the cold rebuild bit-identical to the pre-death snapshot, every
    # preempt/gang machine folded (only live carves stay open), and
    # zero replay errors
    ("preempt_crash_clean",
     lambda l: (None if _dig(l, "extra", "config_17_carve_journal",
                             "recovery") is None
                else bool(_dig(l, "extra", "config_17_carve_journal",
                               "tax_gate"))
                and bool(_dig(l, "extra", "config_17_carve_journal",
                              "recovery", "recovered_bitident"))
                and _dig(l, "extra", "config_17_carve_journal",
                         "recovery", "errors") == 0
                and _dig(l, "extra", "config_17_carve_journal",
                         "non_carve_open_after") == 0)),
    # the soft-row filter contract: device rows equal the host loop's
    # exact-int algebra cell for cell, no probe fallback fired, and zone
    # steering never inflated the fleet past the 1% gate
    ("soft_affinity_clean",
     lambda l: (None if _dig(l, "extra", "config_18_soft_affinity",
                             "row_divergence") is None
                else _dig(l, "extra", "config_18_soft_affinity",
                          "row_divergence") == 0
                and _dig(l, "extra", "config_18_soft_affinity",
                         "unverified") == 0
                and (_dig(l, "extra", "config_18_soft_affinity",
                          "node_regression_pct") or 0.0) <= 1.0)),
]


def load_lines(root: str) -> list:
    """Chronological [(round, variant, bench-line)] — same file set,
    wrapper unwrapping, and sort order as tools/bench_history.py."""
    out, bad = [], []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _NAME.search(os.path.basename(path))
        if not m:
            continue
        rnd, variant = m.group(1), m.group(2) or "-"
        try:
            with open(path) as f:
                line = json.load(f)
        except (OSError, ValueError) as e:
            bad.append(f"{os.path.basename(path)}: {e}")
            continue
        if (isinstance(line, dict) and "metric" not in line
                and isinstance(line.get("line"), dict)):
            line = line["line"]
        if isinstance(line, dict) and "metric" not in line and "tail" in line:
            line = _from_tail(line.get("tail", ""))
        if isinstance(line, dict):
            out.append((rnd, variant, line))
    for b in bad:
        print(f"bench-regress: skipped {b}", file=sys.stderr)
    out.sort(key=lambda r: (r[0], r[1]))
    return out


def check(lines: list) -> tuple:
    """([report rows], [regression strings])."""
    rows, regressions = [], []
    for name, extract, direction, tol in SERIES:
        vals = [(rnd, variant, v) for rnd, variant, line in lines
                for v in [extract(line)]
                if isinstance(v, (int, float))]
        if not vals:
            rows.append((name, 0, "-", "-", "n/a (never measured)"))
            continue
        latest_rnd, latest_var, latest = vals[-1]
        prior = [v for _, _, v in vals[:-1]]
        if not prior:
            rows.append((name, 1, "-", latest,
                         f"ok (single entry, {latest_rnd})"))
            continue
        best = max(prior) if direction == "higher" else min(prior)
        if direction == "higher":
            delta = (latest - best) / best if best else 0.0
            ok = latest >= (1.0 - tol) * best
        else:
            delta = (latest - best) / best if best else 0.0
            ok = latest <= (1.0 + tol) * best
        cell = (f"ok ({delta:+.1%} within {tol:.0%})" if ok
                else f"REGRESSED ({delta:+.1%} beyond {tol:.0%})")
        rows.append((name, len(vals), best, latest, cell))
        if not ok:
            regressions.append(
                f"{name}: {latest} at {latest_rnd}/{latest_var} vs best "
                f"prior {best} ({delta:+.1%}, tolerance {tol:.0%})")
    for name, extract in FLAGS:
        vals = [(rnd, v) for rnd, variant, line in lines
                for v in [extract(line)] if v is not None]
        if not vals:
            rows.append((name, 0, "-", "-", "n/a (never reported)"))
            continue
        rnd, ok = vals[-1]
        rows.append((name, len(vals), "-", ok,
                     "ok" if ok else "REGRESSED (parity broken)"))
        if not ok:
            regressions.append(f"{name}: latest round {rnd} broke parity")
    return rows, regressions


def render(rows: list) -> str:
    headers = ("series", "n", "best_prior", "latest", "verdict")
    table = [list(headers)] + [[str(c) for c in r] for r in rows]
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    out = []
    for n, row in enumerate(table):
        out.append("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
        if n == 0:
            out.append("  ".join("-" * w for w in widths))
    return "\n".join(out)


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or ["."])[0]
    lines = load_lines(root)
    if not lines:
        print(f"bench-regress: no BENCH_r*.json under {root}",
              file=sys.stderr)
        return 1
    rows, regressions = check(lines)
    print(render(rows))
    checked = sum(1 for r in rows if r[1])
    if regressions:
        print(f"bench-regress: {checked} series checked, "
              f"{len(regressions)} regression(s) — FAIL", file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    print(f"bench-regress: {checked} series checked, 0 regressions — PASS",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
