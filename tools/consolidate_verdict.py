"""Consolidation window verdict: one human-readable line from the bench JSON.

`make bench-consolidate` pipes bench.py's stdout through this filter. The
bench line passes through UNCHANGED on stdout (so `> BENCH_rNN.json`
redirects still capture the pure JSON); the verdict goes to stderr:

    consolidate window: 384 candidates, one batched solve \
(device-whatif) 15.3x vs host-incremental, parity=True, 384 drains \
(0 unverified) reclaiming $1843.20/h, relax=fallback-costlier — PASS

PASS needs >= 100 candidates in ONE batched solve, batched
candidate-evaluations/sec >= 10x the host-incremental leg, exact
feasibility parity, and zero unverified drains (every executed drain
re-verified by an independent place_onto replay) — the round-9
acceptance gate.

When the run carried ``--trace TRACE_replay.json`` (bench-replay's
recorded diurnal shape fed into the scale-down window), the trace leg
must ALSO hold: per-phase feasibility parity and zero unverified drains
at every phase of the recorded curve, and shape consistency — the
diurnal trough phase drains at least as many candidates as the peak
phase (scale-down capacity appears when the recorded load recedes). A
skipped trace leg (no --trace, or no trace file yet) leaves the gate
N/A, labelled in the verdict line.
"""

from __future__ import annotations

import json
import sys

GATE_CANDIDATES = 100
GATE_SPEEDUP = 10.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_5_consolidate_2k_nodes", {})
    if "error" in cfg or "consolidation_window" not in cfg:
        return ("consolidate window: no consolidation_window in bench line "
                f"({cfg.get('error', 'config_5 not run')}) — NO VERDICT")
    w = cfg["consolidation_window"]
    candidates = w.get("candidates", 0)
    speedup = w.get("speedup")
    parity = w.get("parity")
    unverified = w.get("unverified_drains")
    relax = w.get("relax") or {}
    relax_note = relax.get("reason", "not-run")
    trace = cfg.get("trace_leg") or {}
    if not trace or "skipped" in trace:
        trace_cell = f"trace={trace.get('skipped', 'n/a')}"
        trace_ok = True  # N/A: the leg wasn't requested or has no input yet
    else:
        ph = trace.get("phases") or []
        ph_ok = all(p.get("parity") is True
                    and p.get("unverified_drains") == 0 for p in ph)
        trace_ok = bool(ph) and ph_ok and trace.get("shape_consistent") is True
        trace_cell = (f"trace={len(ph)}ph diurnal drains "
                      f"{trace.get('drains_trough')}(trough).."
                      f"{trace.get('drains_peak')}(peak)")
    head = (f"consolidate window: {candidates} candidates, one batched solve "
            f"({w.get('executor')}) {speedup}x vs host-incremental "
            f"({w.get('batched_evals_per_s')} vs "
            f"{w.get('host_incremental_evals_per_s')} evals/s), "
            f"parity={parity}, {w.get('drains')} drains "
            f"({unverified} unverified) reclaiming "
            f"${w.get('reclaimed_per_hour', 0):.2f}/h, relax={relax_note}, "
            f"{trace_cell}")
    ok = (candidates >= GATE_CANDIDATES
          and speedup is not None and speedup >= GATE_SPEEDUP
          and parity is True and unverified == 0 and trace_ok)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_CANDIDATES} candidates, >={GATE_SPEEDUP}x, "
            "parity, 0 unverified, trace leg parity+shape when run)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("consolidate window: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
