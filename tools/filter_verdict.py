"""Device-filter verdict: one human-readable line from the bench JSON.

`make bench-filter` pipes bench.py (``--only config_12``) through this
filter. The bench line passes through UNCHANGED on stdout (so
`> BENCH_rNN.json` redirects still capture the pure JSON); the verdict
goes to stderr:

    device filter: 24-schedule windows x 400 types, fused bit-plane \
filter 4.1x vs host columnar, divergence=0, node_parity=True \
(10008 pods), plane reuses +40, steady allocations +0 — PASS

PASS needs (the round-12 acceptance gate):
- device-fused filter stage >= 2x the host columnar leg (p50), cycling
  more constraint variants than the host mask cache holds;
- zero verdict divergence — the bit-plane mask equals the host columnar
  mask bit for bit on every variant;
- node parity: the full 10k-pod solve_batch produces identical node
  counts filter-on and filter-off (the device verdict is a filter, never
  a commit);
- the steady-state residency claim: plane ring reuses INCREASED during
  the timed loop and fresh device allocations did NOT (the bit-planes
  live on device; only the small row stacks cross PCIe).
"""

from __future__ import annotations

import json
import sys

GATE_SPEEDUP = 2.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_12_device_filter", {})
    if "error" in cfg or "speedup" not in cfg:
        return ("device filter: no config_12_device_filter in bench line "
                f"({cfg.get('error', 'config_12 not run')}) — NO VERDICT")
    speedup = cfg.get("speedup")
    divergence = cfg.get("verdict_divergence")
    nparity = cfg.get("node_parity")
    reuses = cfg.get("plane_ring_reuses", 0)
    allocs = cfg.get("steady_allocations")
    head = (f"device filter: {cfg.get('schedules_per_window')}-schedule "
            f"windows x {cfg.get('types')} types, fused bit-plane filter "
            f"{speedup}x vs host columnar, divergence={divergence}, "
            f"node_parity={nparity} ({cfg.get('pods')} pods), "
            f"plane reuses +{reuses:g}, steady allocations +{allocs}")
    ok = (speedup is not None and speedup >= GATE_SPEEDUP
          and divergence == 0 and nparity is True
          and reuses > 0 and allocs == 0)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_SPEEDUP}x, 0 divergence, node parity, "
            "reuses>0, 0 steady allocations)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("device filter: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
