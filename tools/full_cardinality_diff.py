"""One-off full-size high-cardinality differential (VERDICT r3 item 5).

Runs the per-pod C++ kernel (native/ffd.cc kt_ffd_pack_per_pod) against the
Python per-pod oracle (solver/host_ffd.py) at the FULL bench config-6b
scale — 50k pods, 25k distinct shapes, 400 types — asserting exact result
keys (per-node pod sets, instance-type options, node count, unschedulable
set). This is the regime where the C++ kernel's skip-list/cpu-jump
optimizations matter most and where the in-bench check was previously
subsampled to 1.5k shapes. Hours are acceptable; the result is recorded in
CARDINALITY_DIFF.json and cited by BASELINE.md.

Usage: python tools/full_cardinality_diff.py [--pods N] [--shapes N]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def mkpods(n, distinct, seed):
    from karpenter_tpu.api.core import (
        Container, Pod, PodSpec, ResourceRequirements,
    )

    rng = random.Random(seed)
    shapes = set()
    while len(shapes) < distinct:
        shapes.add((rng.randint(50, 4000), rng.randint(64, 4096)))
    shapes = sorted(shapes)
    return [
        Pod(spec=PodSpec(containers=[Container(
            resources=ResourceRequirements.make(requests={
                "cpu": f"{c}m", "memory": f"{m}Mi"}))]))
        for i in range(n) for c, m in (shapes[i % len(shapes)],)
    ]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=50_000)
    ap.add_argument("--shapes", type=int, default=25_000)
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--out", default="CARDINALITY_DIFF.json")
    args = ap.parse_args()

    from bench import make_catalog
    from karpenter_tpu.controllers.provisioning import universe_constraints
    from karpenter_tpu.solver import host_ffd
    from karpenter_tpu.solver.adapter import build_packables_cached, pod_vectors
    from karpenter_tpu.solver.native_ffd import solve_ffd_per_pod_native

    catalog = make_catalog(400)
    constraints = universe_constraints(catalog)
    print(f"building {args.pods} pods / {args.shapes} shapes", flush=True)
    pods = mkpods(args.pods, args.shapes, seed=args.seed)
    for i, p in enumerate(pods):
        p.metadata.name = f"hc-{i}"
    packables, _ = build_packables_cached(catalog, constraints, pods, [])
    vecs, ids = pod_vectors(pods), list(range(len(pods)))

    t0 = time.perf_counter()
    native = solve_ffd_per_pod_native(vecs, ids, packables)
    t_native = time.perf_counter() - t0
    if native is None:
        print("no C++ toolchain; aborting", file=sys.stderr)
        return 1
    print(f"native: {native.node_count} nodes in {t_native:.1f}s", flush=True)

    t0 = time.perf_counter()
    oracle = host_ffd.pack(vecs, ids, packables)
    t_oracle = time.perf_counter() - t0
    print(f"python oracle: {oracle.node_count} nodes in {t_oracle:.1f}s",
          flush=True)

    # exact comparison: node count, unschedulable set, and the full
    # node-by-node packing structure (type options + pod-id sets)
    def key(res):
        return (
            res.node_count,
            sorted(res.unschedulable),
            sorted(
                (tuple(pk.instance_type_indices), pk.node_quantity,
                 tuple(sorted(tuple(sorted(n)) for n in pk.pod_ids)))
                for pk in res.packings),
        )

    k_native, k_oracle = key(native), key(oracle)
    exact = k_native == k_oracle
    out = {
        "pods": args.pods, "distinct_shapes": args.shapes,
        "types": 400, "seed": args.seed,
        "native_node_count": native.node_count,
        "oracle_node_count": oracle.node_count,
        "native_s": round(t_native, 2), "oracle_s": round(t_oracle, 2),
        "exact_full_size": exact,
    }
    if not exact:
        out["divergence"] = {
            "node_count": [native.node_count, oracle.node_count],
            "unschedulable_delta": len(set(k_native[1]) ^ set(k_oracle[1])),
        }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out), flush=True)
    return 0 if exact else 2


if __name__ == "__main__":
    sys.exit(main())
