"""Gang co-pack verdict: one human-readable line from the bench JSON.

`make bench-gang` pipes bench.py (``--only config_11``) through this
filter. The bench line passes through UNCHANGED on stdout (so
`> BENCH_rNN.json` redirects still capture the pure JSON); the verdict
goes to stderr:

    gang co-pack: 256 gangs / 768 members, one batched solve \
(device-gang) 6.5x vs per-gang host loop, verdict_parity=True, \
node_parity=True, 256 placed (0 unverified) — PASS

PASS needs (the round-11 acceptance gate):
- >= 256 gangs solved in ONE batched device dispatch;
- batched solve >= 5x the per-gang sequential host loop (p50);
- exact parity: identical (feasible, slots) verdicts AND node-for-node
  identical plans between the two legs;
- zero unverified placements — every gang that binds was re-verified on
  exact host nano ints against the running pool (the device verdict is
  a filter, never a commit).
"""

from __future__ import annotations

import json
import sys

GATE_GANGS = 256
GATE_SPEEDUP = 5.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_11_gang_copack", {})
    if "error" in cfg or "gangs" not in cfg:
        return ("gang co-pack: no config_11_gang_copack in bench line "
                f"({cfg.get('error', 'config_11 not run')}) — NO VERDICT")
    gangs = cfg.get("gangs", 0)
    speedup = cfg.get("speedup")
    vparity = cfg.get("verdict_parity")
    nparity = cfg.get("node_parity")
    unverified = cfg.get("unverified_placements")
    head = (f"gang co-pack: {gangs} gangs / {cfg.get('members')} members, "
            f"one batched solve ({cfg.get('executor')}) {speedup}x vs "
            f"per-gang host loop, verdict_parity={vparity}, "
            f"node_parity={nparity}, {cfg.get('placed_gangs')} placed "
            f"({unverified} unverified)")
    ok = (gangs >= GATE_GANGS
          and speedup is not None and speedup >= GATE_SPEEDUP
          and vparity is True and nparity is True and unverified == 0)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_GANGS} gangs, >={GATE_SPEEDUP}x, parity, "
            "0 unverified)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("gang co-pack: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
