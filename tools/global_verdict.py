"""Global-window verdict: one human-readable line from the bench JSON.

`make bench-global` pipes bench.py (``--only config_14``) through this
filter. The bench line passes through UNCHANGED on stdout (so
`> BENCH_rNN.json` redirects still capture the pure JSON); the verdict
goes to stderr:

    global window: 12 schedules x 6 types, fleet $120.46/h vs FFD \
$137.64/h (12.48% cheaper, 3 accepted), p99 59.7ms <= 200.0ms budget, \
decline_parity=True killswitch=True, unverified=0 — PASS

PASS needs (the round-14 acceptance gate):
- the joint window plan is >= 5% cheaper per hour than per-schedule
  exact FFD (or places strictly fewer nodes), with the cost computed by
  the controller's substitution rule — accepted schedules contribute
  their rounded plan, declined ones their untouched FFD plan — in exact
  int micro-$;
- at least one schedule accepted (the relaxation actually fired, the
  saving is not vacuous);
- window p99 inside the budget: the global solve rides the dispatch
  stage concurrent with the per-schedule batch, so the provisioning p99
  is unchanged as long as the global leg fits max(200ms, 5x FFD p99);
- exact-FFD parity on every decline: the single-type window (where
  restricted rounding can never win) returns all-None results with
  fallback-prefixed reasons — the controller keeps the FFD plan
  byte-for-byte;
- zero unverified placements: no plan that failed the host int replay
  (verify_plan) was ever accepted;
- the KARPENTER_GLOBAL_SOLVE=0 kill switch reads as disabled.
"""

from __future__ import annotations

import json
import sys

GATE_SAVING_PCT = 5.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_14_global_window", {})
    if "error" in cfg or "saving_pct" not in cfg:
        return ("global window: no config_14_global_window in bench line "
                f"({cfg.get('error', cfg.get('skipped', 'config_14 not run'))})"
                " — NO VERDICT")
    saving = cfg.get("saving_pct")
    cheaper = (saving is not None and saving >= GATE_SAVING_PCT) or (
        cfg.get("global_nodes") is not None
        and cfg.get("ffd_nodes") is not None
        and cfg["global_nodes"] < cfg["ffd_nodes"])
    head = (f"global window: {cfg.get('schedules')} schedules x "
            f"{cfg.get('types')} types, fleet "
            f"${cfg.get('global_cost_per_hour')}/h vs FFD "
            f"${cfg.get('ffd_cost_per_hour')}/h ({saving}% cheaper, "
            f"{cfg.get('accepted')} accepted), p99 "
            f"{cfg.get('global_p99_ms')}ms <= {cfg.get('p99_budget_ms')}ms "
            f"budget, decline_parity={cfg.get('decline_parity')} "
            f"killswitch={cfg.get('killswitch_gate')}, "
            f"unverified={cfg.get('unverified')}")
    ok = (cheaper and (cfg.get("accepted") or 0) >= 1
          and cfg.get("p99_ok") is True
          and cfg.get("decline_parity") is True
          and cfg.get("killswitch_gate") is True
          and cfg.get("unverified") == 0)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_SAVING_PCT}% cheaper or fewer nodes, >=1 "
            "accepted, p99 in budget, decline parity, kill switch, "
            "0 unverified)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("global window: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
