"""Marshal-delta verdict: one human-readable line from the bench JSON.

`make bench-marshal` pipes bench.py's stdout through this filter. The
bench line passes through UNCHANGED on stdout (so `> BENCH_rNN.json`
redirects still capture the pure JSON); the verdict goes to stderr:

    marshal delta: 3.98x (p50 31.5ms vs 125.5ms cold) delta_frac=0.10 \
encode_parity=True solve_parity=True catalog_transfers=0 — PASS (>=3x)

PASS needs speedup >= 3 at steady state (the round-10 acceptance gate),
bit-for-bit encode parity across every window, node-count + bound-set
parity on the end-to-end solve, and zero fresh catalog device transfers
on the donate-leg repeat solve.
"""

from __future__ import annotations

import json
import sys

GATE_SPEEDUP = 3.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_10_marshal_delta", {})
    if "error" in cfg or "speedup" not in cfg:
        return ("marshal delta: no config_10 in bench line "
                f"({cfg.get('error', 'config_10 not run')}) — NO VERDICT")
    speedup = cfg.get("speedup")
    frac = cfg.get("delta_fraction")
    enc_par = cfg.get("encode_parity")
    solve_par = cfg.get("solve_parity")
    transfers = cfg.get("fresh_catalog_transfers")
    ring = cfg.get("steady_ring", {})
    head = (f"marshal delta: {speedup}x "
            f"(p50 {cfg.get('delta_p50_ms')}ms vs "
            f"{cfg.get('cold_p50_ms')}ms cold) "
            f"delta_frac={frac} encode_parity={enc_par} "
            f"solve_parity={solve_par} catalog_transfers={transfers} "
            f"ring={ring.get('allocations', '?')} allocs/"
            f"{ring.get('refills', '?')} refills/"
            f"{ring.get('reuses', '?')} reuses")
    ok = (speedup is not None and speedup >= GATE_SPEEDUP
          and enc_par is True and solve_par is True and transfers == 0)
    return f"{head} — {'PASS' if ok else 'FAIL'} (gate >={GATE_SPEEDUP}x)"


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("marshal delta: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
