"""Metrics lint: every registered series must be documented.

``make metrics-lint`` (tier-1 tooling) fails if any metric registered in
the process-wide registry

- lacks help text (renders without a ``# HELP`` line on /metrics), or
- is absent from the docs metric tables (``karpenter_<name>`` must
  appear somewhere under docs/ — the canonical tables live in
  docs/observability.md).

The import list below is the closed set of modules that register
metrics; a new registration site must be added here or its metrics
escape the lint (the test in tests/test_obs.py greps for call sites to
keep the list honest).
"""

from __future__ import annotations

import glob
import importlib
import os
import sys

# Runnable as `python tools/metrics_lint.py`: sys.path[0] is tools/, so
# the package root must be added before the karpenter_tpu imports.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# Every module with a top-level metric registration (grep for
# DEFAULT/HISTOGRAMS .gauge(/.counter(/.histogram( to regenerate).
REGISTERING_MODULES = [
    "karpenter_tpu.metrics.core",
    "karpenter_tpu.metrics.consolidation",
    "karpenter_tpu.metrics.pipeline",
    "karpenter_tpu.metrics.pressure",
    "karpenter_tpu.metrics.filter",
    "karpenter_tpu.metrics.gang",
    "karpenter_tpu.metrics.global_solve",
    "karpenter_tpu.metrics.marshal",
    "karpenter_tpu.metrics.policy",
    "karpenter_tpu.metrics.recovery",
    "karpenter_tpu.metrics.slo",
    "karpenter_tpu.metrics.topology",
    "karpenter_tpu.solver.solve",
    "karpenter_tpu.solver.hedge",
    "karpenter_tpu.controllers.provisioning",
    "karpenter_tpu.controllers.metrics_controllers",
    "karpenter_tpu.controllers.gc",
]


def lint(docs_glob: str = os.path.join(_ROOT, "docs", "*.md")) -> list:
    for mod in REGISTERING_MODULES:
        importlib.import_module(mod)
    from karpenter_tpu.metrics.registry import DEFAULT, NAMESPACE

    docs_text = ""
    for path in sorted(glob.glob(docs_glob)):
        with open(path) as f:
            docs_text += f.read()
    problems = []
    registered = DEFAULT.registered()
    if not registered:
        return ["no metrics registered — import list is broken"]
    for name, metric in sorted(registered.items()):
        if not getattr(metric, "help", ""):
            problems.append(f"{name}: no help text (add it to the "
                            "registration site or metrics/core.py)")
        if f"{NAMESPACE}_{name}" not in docs_text:
            problems.append(f"{name}: {NAMESPACE}_{name} missing from the "
                            "docs metric tables (docs/observability.md)")
    return problems


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    problems = lint()
    if problems:
        for p in problems:
            print(f"metrics-lint: {p}", file=sys.stderr)
        print(f"metrics-lint: FAIL ({len(problems)} problem(s))",
              file=sys.stderr)
        return 1
    from karpenter_tpu.metrics.registry import DEFAULT

    print(f"metrics-lint: OK ({len(DEFAULT.registered())} metrics, "
          "all helped + documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
