"""Pipeline A/B verdict: one human-readable line from the bench JSON.

`make bench-pipeline` pipes bench.py's stdout through this filter. The
bench line passes through UNCHANGED on stdout (so `> BENCH_rNN.json`
redirects still capture the pure JSON); the verdict goes to stderr:

    pipeline A/B: 1.31x (depth 2 vs 1) devices=2 nodes_equal=True \
fallbacks=none ring=0 allocs steady — PASS (>1.2x)

PASS needs speedup > 1.2 at device_count >= 2 with nodes_equal and no
pipeline-attributable executor fallbacks (the round-8 acceptance gate).
On fewer devices (or a 1-core host) the line still prints, labelled
with why the gate is not applicable.
"""

from __future__ import annotations

import json
import sys

GATE_SPEEDUP = 1.2
GATE_DEVICES = 2
GATE_SLO_TAX_PCT = 1.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_7_control_plane_10k_pods", {})
    if "error" in cfg or "pipeline_ab" not in cfg:
        return ("pipeline A/B: no pipeline_ab in bench line "
                f"({cfg.get('error', 'config_7 not run')}) — NO VERDICT")
    ab = cfg["pipeline_ab"]
    speedup = ab.get("speedup")
    devices = ab.get("device_count") or extra.get("device_count")
    nodes_equal = ab.get("nodes_equal")
    # a pipeline-attributable fallback = executor counts that differ
    # between the legs (e.g. host/native solves only in the pipelined one)
    fallbacks = "none" if (ab.get("executors_pipelined")
                           == ab.get("executors_serial")) else (
        f"EXECUTOR DRIFT {ab.get('executors_pipelined')} "
        f"vs {ab.get('executors_serial')}")
    ring = ab.get("ring_pipelined", {})
    ring_note = (f"ring={ring.get('allocations', '?')} allocs/"
                 f"{ring.get('refills', '?')} refills")
    tax = (cfg.get("trace_overhead") or {}).get("est_tax_pct")
    tax_note = f" trace_tax={tax}%" if tax is not None else ""
    slo_tax = (cfg.get("slo_overhead") or {}).get("est_tax_pct")
    slo_note = f" slo_tax={slo_tax}%" if slo_tax is not None else ""
    head = (f"pipeline A/B: {speedup}x (depth {ab.get('depth_pipelined')} "
            f"vs {ab.get('depth_serial')}) devices={devices} "
            f"nodes_equal={nodes_equal} fallbacks={fallbacks} "
            f"{ring_note}{tax_note}{slo_note}")
    # enabled-path SLO stamping must stay under 1% of the stamped run's
    # wall — gated whenever the bench measured it, even at 1 device
    slo_fail = (slo_tax is not None and slo_tax > GATE_SLO_TAX_PCT)
    if devices is None or devices < GATE_DEVICES:
        if slo_fail:
            return (f"{head} — FAIL (slo_tax {slo_tax}% > "
                    f"{GATE_SLO_TAX_PCT}%)")
        return (f"{head} — GATE N/A (needs device_count >= {GATE_DEVICES}; "
                "rerun with --devices 2)")
    ok = (speedup is not None and speedup > GATE_SPEEDUP
          and nodes_equal and fallbacks == "none" and not slo_fail)
    tail = (f" (slo_tax {slo_tax}% > {GATE_SLO_TAX_PCT}%)"
            if slo_fail else f" (gate >{GATE_SPEEDUP}x)")
    return f"{head} — {'PASS' if ok else 'FAIL'}{tail}"


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("pipeline A/B: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
