"""Policy-scoring verdict: one human-readable line from the bench JSON.

`make bench-policy` pipes bench.py (``--only config_13``) through this
filter. The bench line passes through UNCHANGED on stdout (so
`> BENCH_rNN.json` redirects still capture the pure JSON); the verdict
goes to stderr:

    policy scoring: 24-schedule window x 400 types, device scoring 11.2x \
vs per-cell host loop, row_divergence=0, node_parity=True pick_parity=True \
(9984 pods), unverified=0, frontier 7/7 — PASS

PASS needs (the round-13 acceptance gate):
- device window scoring >= 5x the per-cell host loop (p50), with the
  probe re-verification's cost timed INSIDE the device leg;
- zero default-policy row divergence — the device row equals
  encode_prices of the host scalar scores bit for bit on every member
  (the default policy's differential guarantee);
- node parity AND launch-pick parity: the full 10k-pod solve_batch under
  the interruption-priced policy produces identical node counts and
  identical first-option types with device scoring on and off (the
  device score is a filter-verified pricing input, never a commit);
- zero unverified placements: no score-mismatch fallback fired, i.e.
  every device row that reached the pack kernel survived the probe
  check against the scalar mirror;
- the repack-cost frontier holds at every sweep point: spot selected
  exactly when rate x repack < price x (1 - spot_factor), with nodes
  actually placed at each point (no vacuous sweep).
"""

from __future__ import annotations

import json
import sys

GATE_SPEEDUP = 5.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_13_policy_scoring", {})
    if "error" in cfg or "speedup" not in cfg:
        return ("policy scoring: no config_13_policy_scoring in bench line "
                f"({cfg.get('error', cfg.get('skipped', 'config_13 not run'))})"
                " — NO VERDICT")
    speedup = cfg.get("speedup")
    div = cfg.get("row_divergence_default")
    nparity = cfg.get("node_parity")
    pparity = cfg.get("pick_parity")
    unverified = cfg.get("unverified")
    frontier = cfg.get("spot_frontier") or []
    fok = cfg.get("frontier_ok")
    f_held = sum(1 for pt in frontier
                 if pt.get("spot_expected") == pt.get("spot_selected")
                 and pt.get("nodes", 0) > 0)
    head = (f"policy scoring: {cfg.get('schedules_per_window')}-schedule "
            f"window x {cfg.get('types')} types, device scoring {speedup}x "
            f"vs per-cell host loop, row_divergence={div}, "
            f"node_parity={nparity} pick_parity={pparity} "
            f"({cfg.get('pods')} pods), unverified={unverified}, "
            f"frontier {f_held}/{len(frontier)}")
    ok = (speedup is not None and speedup >= GATE_SPEEDUP
          and div == 0 and nparity is True and pparity is True
          and unverified == 0 and fok is True and len(frontier) > 0)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_SPEEDUP}x, 0 divergence, node+pick parity, "
            "0 unverified, frontier holds at every repack point)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("policy scoring: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
