"""Cluster-in-a-box replay CLI (docs/scale.md §3).

Runs karpenter_tpu.replay against the in-process control plane and prints
ONE JSON line: ``{"replay": <SLO report>, "store_ab": <A/B or null>}`` —
pipe through ``tools/replay_verdict.py`` for the pass/fail gate line:

    JAX_PLATFORMS=cpu python tools/replay.py --pods 10000 --shards 2 \
        | python tools/replay_verdict.py

``make bench-replay`` runs the full million-pod shape through bench.py's
supervisor instead (config_9), which adds backend probing and the
BENCH-line format; this CLI is the dev-loop entry for custom shapes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "replay", description="traffic replay against the sharded control plane")
    p.add_argument("--pods", type=int, default=1_000_000,
                   help="total offered pods (flood + cohort + churn)")
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--tenants", type=int, default=8)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--cohort", type=int, default=2_000,
                   help="pods driven through the full create→bind path")
    p.add_argument("--churn", type=int, default=2_000,
                   help="short-lived pods created then deleted a tick later")
    p.add_argument("--max-depth", type=int, default=20_000,
                   help="per-shard batcher depth bound")
    p.add_argument("--ticks", type=int, default=24)
    p.add_argument("--settle", type=float, default=180.0)
    p.add_argument("--no-chaos", action="store_true",
                   help="skip the seeded FaultPlan + ChaosKube wrapper")
    p.add_argument("--no-store-ab", action="store_true",
                   help="skip the 100k-object store list-by-kind A/B leg")
    p.add_argument("--store-objects", type=int, default=100_000)
    p.add_argument("--store-minority", type=int, default=2_000)
    args = p.parse_args(argv)

    from karpenter_tpu.replay import ReplayConfig, run_replay, store_ab

    cfg = ReplayConfig(
        pods_total=args.pods, shards=args.shards, tenants=args.tenants,
        seed=args.seed, bound_cohort=args.cohort, churn_pods=args.churn,
        max_depth=args.max_depth, ticks=args.ticks, settle_s=args.settle,
        chaos=not args.no_chaos)
    report = run_replay(cfg)
    ab = None
    if not args.no_store_ab:
        ab = store_ab(objects=args.store_objects,
                      minority=args.store_minority)
    print(json.dumps({"replay": report, "store_ab": ab}))
    return 0 if report.get("completed") else 1


if __name__ == "__main__":
    sys.exit(main())
