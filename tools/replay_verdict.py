"""Replay verdict: one human-readable gate line from the replay JSON.

``make bench-replay`` pipes bench.py (``--only config_9``) through this
filter; ``tools/replay.py``'s output is accepted too. The JSON passes
through UNCHANGED on stdout (so ``> BENCH_rNN.json`` redirects still
capture it); the verdict goes to stderr:

    replay: 1000000 pods / 4 shards peak=L2 crit_shed=0 recovery=1.2s \
default_p99=0.71s store_scan=33.2x — PASS

PASS needs (the round-9 acceptance gates):
- the replay completed (every surviving cohort pod bound, workers alive,
  ladder released) with >= 99% of the configured pods actually offered;
- ZERO system-critical sheds across the whole replay;
- recovery to L0 after the flood (recovery_to_l0_s present);
- ZERO partial gangs when the run injected gang workloads
  (gang_fraction > 0): every all-or-nothing pod group either bound
  whole or stayed wholly Pending;
- when the run pinned cohort pods to spot (spot_fraction > 0): at least
  one seeded spot-interruption actually fired (chaos on), some cohort
  pods really ran on spot, and every pod displaced by a reclaim REBOUND
  (displaced == rebound) — with zero system-critical sheds throughout;
- store list-by-kind scan speedup >= 5x vs the naive store at the
  A/B leg's object count (absent A/B leg → gate N/A, labelled).
"""

from __future__ import annotations

import json
import sys

GATE_SCAN_SPEEDUP = 5.0
GATE_OFFERED_FRACTION = 0.99


def _extract(line: dict):
    """(replay report, store A/B) from either accepted shape: the bench
    line (config_9 under extra) or tools/replay.py's direct output."""
    if "replay" in line:
        return line.get("replay"), line.get("store_ab")
    cfg = line.get("extra", {}).get("config_9_million_pod_replay", {})
    return cfg.get("replay"), cfg.get("store_ab")


def verdict(line: dict) -> str:
    replay, ab = _extract(line)
    if not replay:
        note = line.get("extra", {}).get(
            "config_9_million_pod_replay", {}).get("error", "no replay run")
        return f"replay: no report in input ({note}) — NO VERDICT"
    cfg = replay.get("config", {})
    want = cfg.get("pods_total", 0)
    offered = replay.get("offered_total", 0)
    crit_shed = replay.get("system_critical_shed")
    recovery = replay.get("recovery_to_l0_s")
    lat = (replay.get("pending_to_bound_s") or {}).get("default") or {}
    scan_x = (ab or {}).get("scan_speedup")
    gangs = replay.get("gangs") or {}
    gang_cell = (f"{gangs.get('gangs_fully_bound')}/"
                 f"{gangs.get('offered_gangs')}"
                 if gangs.get("offered_gangs") else "n/a")
    spot = replay.get("spot") or {}
    spot_cell = (f"{spot.get('rebound')}/{spot.get('displaced')}rebound"
                 f"(intr={spot.get('interruptions')})"
                 if spot else "n/a")
    head = (f"replay: {offered} pods / {cfg.get('shards')} shards "
            f"peak=L{replay.get('peak_level')} crit_shed={crit_shed} "
            f"recovery={recovery}s default_p99={lat.get('p99')}s "
            f"gangs={gang_cell} spot={spot_cell} "
            f"store_scan={scan_x if scan_x is not None else 'n/a'}x")
    problems = []
    if not replay.get("completed"):
        problems.append(f"incomplete (unbound={replay.get('cohort_unbound')},"
                        f" healthy={replay.get('workers_healthy')})")
    if want and offered < GATE_OFFERED_FRACTION * want:
        problems.append(f"offered {offered} < {GATE_OFFERED_FRACTION:.0%} "
                        f"of configured {want}")
    if crit_shed != 0:
        problems.append(f"{crit_shed} system-critical sheds")
    if recovery is None:
        problems.append("never recovered to L0")
    if gangs.get("offered_gangs") and gangs.get("partial_gangs", 0) != 0:
        problems.append(f"{gangs['partial_gangs']} partial gang(s) — "
                        "all-or-nothing invariant broken")
    if spot:
        if spot.get("rebound", 0) != spot.get("displaced", 0):
            problems.append(
                f"{spot.get('displaced', 0) - spot.get('rebound', 0)} "
                "reclaimed pod(s) never rebound")
        if spot.get("cohort_spot_pods", 0) < 1:
            problems.append("spot leg vacuous: no cohort pod pinned to spot")
        if cfg.get("chaos") and spot.get("interruptions", 0) < 1:
            problems.append("spot leg vacuous: no interruption ever fired")
    if ab is None:
        return f"{head} — store GATE N/A (A/B leg not run); replay " + \
            ("PASS" if not problems else f"FAIL ({'; '.join(problems)})")
    if scan_x is None or scan_x < GATE_SCAN_SPEEDUP:
        problems.append(f"store scan speedup {scan_x} < {GATE_SCAN_SPEEDUP}x")
    if problems:
        return f"{head} — FAIL ({'; '.join(problems)})"
    return (f"{head} — PASS (crit_shed=0, L0 recovery, "
            f"scan >= {GATE_SCAN_SPEEDUP}x at {(ab or {}).get('objects')} "
            "objects)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and ("metric" in line
                                           or "replay" in line):
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("replay: no JSON line on stdin — NO VERDICT", file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
