"""SLO verdict: one human-readable gate line for the per-pod SLO engine.

``make bench-replay`` pipes bench.py (``--only config_9``) through
tools/replay_verdict.py and then through this filter. The JSON passes
through UNCHANGED on stdout (redirects still capture it); the verdict
goes to stderr:

    slo: clean trips=0 p99 ok (3 bands) parity=0.58% bounded \
chaos trips=1 band=default/e2e readyz=degraded — PASS

PASS needs (the per-pod SLO engine acceptance gates):
- clean leg: per-band pending→bound p99 within the band's configured
  objective, ZERO burn-sentinel trips, and the engine's bounded-growth
  invariant held (cells ≤ bands × stages, bins ≤ cells × max_bins);
- digest parity (smoke runs): digest p50/p99 within 1% relative error
  of the exact per-pod latency lists (absent on full-scale runs, where
  the lists never materialize — gate N/A, labelled);
- seeded-chaos probe leg: ≥ 1 sentinel trip, tagged with the offending
  band and stage, and readyz degraded while burning (absent probe leg →
  gate N/A, labelled).
"""

from __future__ import annotations

import json
import sys

GATE_PARITY_REL_ERR = 0.01


def _extract(line: dict):
    """(replay report, slo_chaos) from either accepted shape: the bench
    line (config_9 under extra) or tools/replay.py's direct output."""
    if "replay" in line:
        return line.get("replay"), line.get("slo_chaos")
    cfg = line.get("extra", {}).get("config_9_million_pod_replay", {})
    return cfg.get("replay"), cfg.get("slo_chaos")


def verdict(line: dict) -> str:
    replay, chaos = _extract(line)
    if not replay:
        return "slo: no replay report in input — NO VERDICT"
    slo = replay.get("slo") or {}
    burn = slo.get("burn") or {}
    objectives = burn.get("objectives") or {}
    latency = replay.get("pending_to_bound_s") or {}
    problems = []

    # clean leg: per-band p99 within the configured objective
    bands_checked = 0
    for band, obj in objectives.items():
        rep = latency.get(band)
        if not rep or not rep.get("n"):
            continue
        bands_checked += 1
        if rep["p99"] > obj["threshold_s"]:
            problems.append(f"{band} p99 {rep['p99']}s > objective "
                            f"{obj['threshold_s']}s")

    # clean leg: zero sentinel trips, bounded digest growth
    trips = slo.get("trips", 0)
    if trips != 0:
        problems.append(f"{trips} burn trip(s) on the clean leg")
    if not slo.get("bounded", False):
        problems.append(f"digest growth unbounded (cells={slo.get('cells')} "
                        f"bins={slo.get('total_bins')})")

    # digest-vs-exact parity (smoke legs only)
    parity = replay.get("slo_digest_parity")
    parity_cell = "n/a"
    if parity is not None:
        worst = max((e for band in parity.values() if isinstance(band, dict)
                     for e in band.values()), default=0.0)
        parity_cell = f"{worst * 100:.2f}%"
        if not parity.get("within_1pct", False):
            problems.append(f"digest parity {parity_cell} > "
                            f"{GATE_PARITY_REL_ERR:.0%} of exact quantiles")

    # seeded-chaos probe leg: the sentinel must trip, tagged, and degrade
    chaos_cell = "n/a"
    if chaos is not None:
        ctrips = chaos.get("trips", 0)
        tag = chaos.get("last_trip") or {}
        chaos_cell = (f"trips={ctrips} band={tag.get('band')}/"
                      f"{tag.get('stage')} readyz="
                      + ("degraded" if chaos.get("readyz_degraded")
                         else "ok"))
        if ctrips < 1:
            problems.append("chaos probe never tripped the sentinel")
        elif not tag.get("band") or not tag.get("stage"):
            problems.append(f"chaos trip untagged: {tag}")
        if ctrips >= 1 and not chaos.get("readyz_degraded"):
            problems.append("sentinel tripped but readyz never degraded")

    head = (f"slo: clean trips={trips} p99 ok ({bands_checked} bands) "
            f"parity={parity_cell} "
            f"{'bounded' if slo.get('bounded') else 'UNBOUNDED'} "
            f"chaos {chaos_cell}")
    if problems:
        return f"{head} — FAIL ({'; '.join(problems)})"
    return f"{head} — PASS"


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and ("metric" in line
                                           or "replay" in line):
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("slo: no JSON line on stdin — NO VERDICT", file=sys.stderr)
        return 1
    out = verdict(last)
    print(out, file=sys.stderr)
    return 0 if "FAIL" not in out and "NO VERDICT" not in out else 1


if __name__ == "__main__":
    sys.exit(main())
