"""Topology-carve verdict: one human-readable line from the bench JSON.

`make bench-topology` pipes bench.py (``--only config_16``) through this
filter. The bench line passes through UNCHANGED on stdout (so
`> BENCH_rNN.json` redirects still capture the pure JSON); the verdict
goes to stderr:

    topology carve: 24 gangs x 20 nodes (4 empty + 8 contig + 8 scatter), \
16 placed vs 8 shape-only (+100.0%), unverified=0, kernel 0.585ms vs \
scalar 79.061ms (135.2x, device-carve, divergence=0), preemptions=1 \
(sc=0, fresh-cheaper declined), killswitch=True parity=True — PASS

PASS needs (the round-16 acceptance gate, docs/solver.md §19):
- the carve-aware walk places >= 20% more gangs than the conservative
  shape-only baseline on the same saturated fleet (grow=False) — the
  fragmentation harvest is real, not noise;
- zero unverified carves: every committed carve re-validated post hoc
  as exactly one placement-mask row disjoint from the replayed
  occupancy plane (the host cell-by-cell verify is the only committer);
- every scatter-fragmented bin rejected: phantom capacity the
  shape-only gate admits (phantom_gangs_naive > 0 demonstrates the
  trap; carve_rejects > 0 shows the carve walk refusing it);
- the batched carve kernel is >= 5x the scalar host carve loop at p50,
  on the device executor, with bit-identical verdicts (divergence=0);
- >= 1 executed preemption (the priced path is exercised, not vacuous)
  and ZERO system-critical displacements; the overpriced victim
  declined fresh-cheaper — displacement fires exactly when it beats a
  fresh node;
- the KARPENTER_TOPOLOGY_CARVE=0 kill switch reads as disabled and the
  annotation-free encode is bit-for-bit the shape-only encoding.
"""

from __future__ import annotations

import json
import sys

GATE_GAIN_PCT = 20.0
GATE_SPEEDUP = 5.0


def verdict(line: dict) -> str:
    extra = line.get("extra", {})
    cfg = extra.get("config_16_topology_carve", {})
    if "error" in cfg or "gain_pct" not in cfg:
        return ("topology carve: no config_16_topology_carve in bench line "
                f"({cfg.get('error', cfg.get('skipped', 'config_16 not run'))})"
                " — NO VERDICT")
    gain = cfg.get("gain_pct")
    speedup = cfg.get("speedup")
    declines = cfg.get("preempt_declines") or {}
    head = (f"topology carve: {cfg.get('gangs')} gangs x "
            f"{cfg.get('seed_nodes')} nodes ({cfg.get('empty_nodes')} empty "
            f"+ {cfg.get('frag_contiguous')} contig + "
            f"{cfg.get('frag_scattered')} scatter), "
            f"{cfg.get('carve_placed')} placed vs "
            f"{cfg.get('shape_only_placed')} shape-only (+{gain}%), "
            f"unverified={cfg.get('unverified')}, kernel "
            f"{cfg.get('kernel_p50_ms')}ms vs scalar "
            f"{cfg.get('scalar_p50_ms')}ms ({speedup}x, "
            f"{cfg.get('kernel_executor')}, "
            f"divergence={cfg.get('kernel_divergence')}), "
            f"preemptions={cfg.get('preemptions')} "
            f"(sc={cfg.get('system_critical_preemptions')}, "
            f"{'fresh-cheaper declined' if declines.get('fresh-cheaper') else 'no priced decline'}), "
            f"killswitch={cfg.get('killswitch_gate')} "
            f"parity={cfg.get('killswitch_parity')}")
    ok = (gain is not None and gain >= GATE_GAIN_PCT
          and cfg.get("unverified") == 0
          and (cfg.get("phantom_gangs_naive") or 0) > 0
          and (cfg.get("carve_rejects") or 0) > 0
          and speedup is not None and speedup >= GATE_SPEEDUP
          and cfg.get("kernel_executor") == "device-carve"
          and cfg.get("kernel_divergence") == 0
          and (cfg.get("preemptions") or 0) >= 1
          and cfg.get("system_critical_preemptions") == 0
          and (declines.get("fresh-cheaper") or 0) >= 1
          and cfg.get("killswitch_gate") is True
          and cfg.get("killswitch_parity") is True)
    return (f"{head} — {'PASS' if ok else 'FAIL'} "
            f"(gate >={GATE_GAIN_PCT}% more gangs, 0 unverified, kernel "
            f">={GATE_SPEEDUP}x scalar on device with 0 divergence, >=1 "
            "preemption with 0 system-critical, fresh-cheaper priced "
            "decline, kill switch + parity)")


def main() -> int:
    last = None
    for raw in sys.stdin:
        sys.stdout.write(raw)  # pass-through: stdout stays the pure JSON
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
            if isinstance(line, dict) and "metric" in line:
                last = line
        except ValueError:
            continue
    sys.stdout.flush()
    if last is None:
        print("topology carve: no bench JSON line on stdin — NO VERDICT",
              file=sys.stderr)
        return 1
    print(verdict(last), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
