"""Trace analysis: per-window critical path from a Chrome-trace dump.

Two modes:

- ``python tools/traceview.py TRACE.json`` — read a dump written by
  ``karpenter_tpu.obs.trace.dump_chrome`` and print, per window
  (trace id): wall seconds, per-stage totals and % of wall, the
  critical path (stages in start order with exclusive seconds), and
  measured overlap seconds (sum of stage durations minus their union —
  the pipelining win the stage spans actually observed).
- ``... | python tools/traceview.py --bench`` — bench/verdict chaining:
  stdin JSON passes through UNCHANGED on stdout (same contract as
  tools/*_verdict.py), the dump path is located under a ``trace_dump``
  key anywhere in the bench line, and the table goes to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

# Window-root span names emitted by the controllers/bench.
WINDOW_KINDS = ("provision", "consolidate", "replay")

# Trace stage span name -> SLO engine stage (karpenter_tpu.obs.slo.STAGES).
# The trace decomposes the window finer than the SLO engine stamps it, so
# several spans share one digest column (schedule = close->dispatch covers
# feasibility, marshal, and dispatch).
_SLO_STAGE = {"intake": "intake", "feasibility": "schedule",
              "marshal": "schedule", "dispatch": "schedule",
              "device_solve": "solve", "launch_bind": "bind", "bind": "bind"}


def _spans(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("ph") == "X" and "dur" in e]


def _union_seconds(ivals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [t0, t1) intervals (µs in, s out)."""
    total = 0.0
    end = None
    for t0, t1 in sorted(ivals):
        if end is None or t0 > end:
            total += t1 - t0
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total / 1e6


def _exclusive_seconds(stages: List[dict]) -> Dict[str, float]:
    """Sweep-line: at every instant covered by >=1 stage span, charge the
    latest-starting active span. The result is each stage's share of the
    critical path (the wall time it alone accounts for)."""
    points: List[Tuple[float, int, dict]] = []
    for s in stages:
        points.append((s["ts"], 1, s))
        points.append((s["ts"] + s["dur"], 0, s))
    points.sort(key=lambda p: (p[0], p[1]))
    active: List[dict] = []
    excl: Dict[str, float] = {}
    prev = None
    for t, kind, s in points:
        if active and prev is not None and t > prev:
            top = max(active, key=lambda a: a["ts"])
            excl[top["name"]] = excl.get(top["name"], 0.0) + (t - prev) / 1e6
        if kind == 1:
            active.append(s)
        else:
            active.remove(s)
        prev = t
    return excl


def analyze(events: List[dict]) -> List[Dict[str, Any]]:
    """One report dict per window trace found in the event list."""
    by_trace: Dict[str, List[dict]] = {}
    for e in _spans(events):
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    reports = []
    for tid, evs in sorted(by_trace.items()):
        roots = [e for e in evs
                 if not (e.get("args") or {}).get("parent_id")
                 and e["name"] in WINDOW_KINDS]
        root = max(roots, key=lambda e: e["dur"]) if roots else None
        stages = [e for e in evs if e is not root]
        if root is None and not stages:
            continue
        # wall = the trace's full extent, root included: retroactive
        # children (the intake wait is timed BEFORE its window span
        # opens) extend the window beyond the root span's own duration
        wall = (max(e["ts"] + e["dur"] for e in evs)
                - min(e["ts"] for e in evs)) / 1e6
        totals: Dict[str, float] = {}
        order: Dict[str, float] = {}
        for e in sorted(stages, key=lambda e: e["ts"]):
            totals[e["name"]] = totals.get(e["name"], 0.0) + e["dur"] / 1e6
            order.setdefault(e["name"], e["ts"])
        union = _union_seconds([(e["ts"], e["ts"] + e["dur"])
                                for e in stages])
        overlap = max(0.0, sum(totals.values()) - union)
        reports.append({
            "window": tid,
            "kind": root["name"] if root else "(rootless)",
            "tags": dict((root.get("args") or {})) if root else {},
            "wall_s": wall,
            "stages": totals,
            "first_ts": {k: v for k, v in order.items()},
            "critical_path": _exclusive_seconds(stages),
            "overlap_s": overlap,
            "coverage": (union / wall) if wall else 0.0,
        })
    return reports


def render(reports: List[Dict[str, Any]], out=sys.stdout,
           slo: Optional[Dict[str, Any]] = None) -> None:
    if not reports:
        print("traceview: no window traces in dump", file=out)
        return
    slo_stages = (slo or {}).get("stages") or {}
    print(f"traceview: {len(reports)} window(s)", file=out)
    for r in reports:
        tags = r["tags"]
        extra = "".join(
            f" {k}={tags[k]}" for k in ("shard", "pressure_level", "pods",
                                        "depth", "overlap_s")
            if k in tags)
        print(f"\nwindow {r['window']} ({r['kind']}) "
              f"wall={r['wall_s']:.4f}s overlap={r['overlap_s']:.4f}s "
              f"coverage={r['coverage']:.1%}{extra}", file=out)
        slo_head = (f"{'slo_p50':>10}{'slo_p99':>10}" if slo_stages else "")
        print(f"  {'stage':<16}{'total_s':>10}{'% wall':>9}"
              f"{'critical_s':>12}{slo_head}", file=out)
        wall = r["wall_s"] or 1.0
        crit = r["critical_path"]
        for name in sorted(r["stages"], key=lambda n: r["first_ts"][n]):
            tot = r["stages"][name]
            slo_cols = ""
            if slo_stages:
                rep = slo_stages.get(_SLO_STAGE.get(name, ""))
                slo_cols = (f"{rep['p50']:>10.4f}{rep['p99']:>10.4f}"
                            if rep and rep.get("n") else f"{'-':>10}{'-':>10}")
            print(f"  {name:<16}{tot:>10.4f}{tot / wall:>8.1%}"
                  f"{crit.get(name, 0.0):>12.4f}{slo_cols}", file=out)
        path = " -> ".join(
            f"{n}({crit[n]:.3f}s)"
            for n in sorted(crit, key=lambda n: r["first_ts"].get(n, 0.0)))
        print(f"  critical path: {path}", file=out)
    if slo_stages:
        # Digest columns are PROCESS-CUMULATIVE (every pod since the last
        # engine reset), unlike the per-window span totals above them.
        summary = "  ".join(
            f"{s}: p50={rep['p50']:.4f}s p99={rep['p99']:.4f}s n={rep['n']}"
            for s, rep in slo_stages.items() if rep.get("n"))
        print(f"\nslo digests (cumulative, all bands merged): {summary}",
              file=out)


def _find_key(obj: Any, key: str) -> Optional[Any]:
    if isinstance(obj, dict):
        if key in obj:
            return obj[key]
        for v in obj.values():
            hit = _find_key(v, key)
            if hit is not None:
                return hit
    elif isinstance(obj, list):
        for v in obj:
            hit = _find_key(v, key)
            if hit is not None:
                return hit
    return None


def _bench_mode() -> int:
    """Verdict-chain filter: JSON stdin -> stdout unchanged, table ->
    stderr from the dump named by the line's ``trace_dump`` key."""
    dump_path = None
    marshal = None
    for raw in sys.stdin:
        sys.stdout.write(raw)
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
        except ValueError:
            continue
        hit = _find_key(line, "trace_dump")
        if hit:
            dump_path = hit
        mc = _find_key(line, "config_10_marshal_delta")
        if isinstance(mc, dict) and "speedup" in mc:
            marshal = mc
    sys.stdout.flush()
    if marshal is not None:
        ring = marshal.get("steady_ring", {})
        print(f"traceview: marshal-cache {marshal['speedup']}x delta "
              f"(frac={marshal.get('delta_fraction')}, "
              f"{marshal.get('fresh_catalog_transfers', '?')} fresh catalog "
              f"transfers, ring {ring.get('allocations', '?')} allocs/"
              f"{ring.get('refills', '?')} refills/"
              f"{ring.get('reuses', '?')} reuses)", file=sys.stderr)
    if not dump_path:
        print("traceview: no trace_dump in bench output — NO TABLE",
              file=sys.stderr)
        return 1
    try:
        with open(dump_path) as f:
            dump = json.load(f)
    except OSError as e:
        print(f"traceview: cannot read {dump_path}: {e}", file=sys.stderr)
        return 1
    reports = analyze(dump.get("traceEvents", []))
    render(reports, out=sys.stderr,
           slo=(dump.get("otherData") or {}).get("slo"))
    return 0 if reports else 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        "traceview", description="per-window critical path from a trace dump")
    p.add_argument("dump", nargs="?", help="Chrome-trace JSON path")
    p.add_argument("--bench", action="store_true",
                   help="stdin-passthrough mode for bench verdict chains")
    args = p.parse_args(argv)
    if args.bench:
        return _bench_mode()
    if not args.dump:
        p.error("a dump path is required outside --bench mode")
    with open(args.dump) as f:
        dump = json.load(f)
    reports = analyze(dump.get("traceEvents", []))
    render(reports, slo=(dump.get("otherData") or {}).get("slo"))
    return 0 if reports else 1


if __name__ == "__main__":
    sys.exit(main())
